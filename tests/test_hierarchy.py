"""Hierarchy-aware tiered collectives (ISSUE 15 tentpole).

Coverage contract (the ISSUE's satellite list):

* bit-parity flat-vs-hierarchical for exact modes — exactly-summable
  payloads (integer-valued floats) so association cannot leak into the
  oracle; pure data movement (gather / all-to-all) is bit-identical for
  ANY payload — across topologies (4 = 2×2, 8 = 2×4, degenerate 1×N and
  N×1) and padded (non-divisible) shapes;
* HLO-audit zero drift with per-tier replica-group assertions — the
  emitted replica groups ARE the ground truth for which tier a hop
  rides, and the cross-node all-reduce's per-participant payload is
  exactly the 1/local shard of the flat payload;
* per-tier ``precision=`` composition bounds (cross tier compressed,
  in-node exact);
* zero-recompile repeat dispatch of the tiered programs;
* DASO refactor equivalence: its send kernel — now routed through
  :func:`heat_tpu.core.topology.node_mean_cross_sum` — bit-equals the
  legacy hand-rolled node-group collective (the PR 9 bf16-subsumption
  contract, extended).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import heat_tpu as ht
from heat_tpu.core import collective_prec, topology
from heat_tpu.core.communication import MeshCommunication
from heat_tpu.telemetry import collectives as model, hlo


def _subcomm(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs >= {n} devices")
    return MeshCommunication(devices=devs[:n])


@pytest.fixture
def comm4():
    return _subcomm(4)


def _run(comm, kernel, x, ndim=2, out_ndim=None):
    spec = comm.spec(0, ndim)
    out_spec = spec if out_ndim is None else comm.spec(0, out_ndim)
    return jax.shard_map(
        kernel, mesh=comm.mesh, in_specs=spec, out_specs=out_spec
    )(x)


def _int_valued(shape, scale=8, seed=0):
    """Float payload whose sums are exactly representable — bit-parity
    between summation orders is then a routing oracle, not luck."""
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        np.round(rng.standard_normal(shape) * scale).astype(np.float32)
    )


# -- topology resolution -------------------------------------------------------


class TestTopology:
    def test_parse_grammar(self):
        t = topology.parse("2x4", 8)
        assert (t.node, t.local, t.source) == (2, 4, "knob")
        assert topology.parse("2×4", 8).local == 4  # unicode ×
        assert topology.parse(" 4X2 ", 8).node == 4

    def test_parse_malformed(self):
        for bad in ("", "x", "2x", "ax b", "2x2x2", "-2x4", "0x8"):
            assert topology.parse(bad, 8) is None

    def test_parse_mismatch_warns_and_falls_back(self):
        with pytest.warns(UserWarning, match="falling back"):
            assert topology.parse("3x3", 8) is None

    def test_detect_even_is_daso_split(self):
        t = topology.detect(8)
        assert (t.node, t.local) == (2, 4)
        assert topology.detect(4).node == 2

    def test_detect_odd_is_trivial(self):
        t = topology.detect(5)
        assert (t.node, t.local) == (1, 5) and not t.nontrivial

    def test_groups_partition_the_mesh(self):
        t = topology.Topology(2, 4)
        assert t.node_groups() == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert t.cross_groups() == [[0, 4], [1, 5], [2, 6], [3, 7]]
        flat = sorted(i for g in t.node_groups() for i in g)
        assert flat == list(range(8))

    def test_active_requires_opt_in_and_nontrivial(self, monkeypatch):
        monkeypatch.delenv("HEAT_TPU_HIERARCHICAL", raising=False)
        assert topology.active(8) is None  # default off
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        assert topology.active(8) is not None
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", "1x8")  # degenerate
        assert topology.active(8) is None
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", "8x1")
        assert topology.active(8) is None

    def test_cross_mode_resolution(self, monkeypatch):
        monkeypatch.delenv("HEAT_TPU_HIERARCHICAL_PREC", raising=False)
        monkeypatch.delenv("HEAT_TPU_COLLECTIVE_PREC", raising=False)
        assert topology.cross_mode(jnp.float32) == "off"
        monkeypatch.setenv("HEAT_TPU_COLLECTIVE_PREC", "bf16")
        assert topology.cross_mode(jnp.float32) == "bf16"
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL_PREC", "int8")
        assert topology.cross_mode(jnp.float32) == "int8"
        # per-call override wins; non-floats always demote to off
        assert topology.cross_mode(jnp.float32, "off") == "off"
        assert topology.cross_mode(jnp.int32) == "off"

    def test_cache_token_tracks_the_knobs(self, monkeypatch):
        monkeypatch.delenv("HEAT_TPU_HIERARCHICAL", raising=False)
        assert topology.cache_token(8) == ("flat",)
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        tok = topology.cache_token(8)
        assert tok[0] == "hier" and tok[1:3] == (2, 4)
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL_PREC", "bf16")
        assert topology.cache_token(8) != tok


# -- flat-vs-tiered bit parity -------------------------------------------------


TOPOLOGIES = [(4, "2x2"), (8, "2x4"), (8, "4x2")]
DEGENERATE = [(4, "1x4"), (4, "4x1"), (8, "1x8")]


class TestTieredParity:
    def _both(self, comm, kernel, x, monkeypatch, ndim=2, out_ndim=None):
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "0")
        flat = np.asarray(_run(comm, kernel, x, ndim, out_ndim))
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        hier = np.asarray(_run(comm, kernel, x, ndim, out_ndim))
        return flat, hier

    @pytest.mark.parametrize("p,topo", TOPOLOGIES + DEGENERATE)
    def test_psum_bit_parity(self, p, topo, monkeypatch):
        comm = _subcomm(p)
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", topo)
        # padded shape: 7 is not divisible by local or p
        x = _int_valued((p, 7))
        xs = jax.device_put(x, comm.sharding(0, 2))
        flat, hier = self._both(
            comm, lambda v: comm.psum(v), xs, monkeypatch
        )
        assert flat.tobytes() == hier.tobytes()
        np.testing.assert_array_equal(
            hier, np.broadcast_to(np.asarray(x).sum(0), (p, 7))
        )

    @pytest.mark.parametrize("p,topo", TOPOLOGIES + DEGENERATE)
    def test_all_gather_bit_parity_any_payload(self, p, topo, monkeypatch):
        comm = _subcomm(p)
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", topo)
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((2 * p, 3)).astype(np.float32))
        xs = jax.device_put(x, comm.sharding(0, 2))
        # gather is pure movement: bit parity holds for ANY payload
        flat, hier = self._both(
            comm, lambda v: comm.all_gather(v)[: v.shape[0]], xs,
            monkeypatch,
        )
        assert flat.tobytes() == hier.tobytes()

    @pytest.mark.parametrize("p,topo", TOPOLOGIES + DEGENERATE)
    def test_all_to_all_bit_parity_any_payload(self, p, topo, monkeypatch):
        comm = _subcomm(p)
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", topo)
        rng = np.random.default_rng(4)
        x = jnp.asarray(
            rng.standard_normal((p, 3 * p)).astype(np.float32)
        )
        xs = jax.device_put(x, comm.sharding(0, 2))
        flat, hier = self._both(
            comm,
            lambda v: comm.all_to_all(v, split_axis=1, concat_axis=0),
            xs, monkeypatch,
        )
        assert flat.tobytes() == hier.tobytes()
        # and the roundtrip is the identity under the tiered lowering
        def roundtrip(v):
            t = comm.all_to_all(v, split_axis=1, concat_axis=0)
            return comm.all_to_all(t, split_axis=0, concat_axis=1)

        out = np.asarray(_run(comm, roundtrip, xs))
        assert out.tobytes() == np.asarray(x).tobytes()

    @pytest.mark.parametrize("p,topo", TOPOLOGIES + DEGENERATE)
    def test_reduce_scatter_bit_parity(self, p, topo, monkeypatch):
        comm = _subcomm(p)
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", topo)
        x = _int_valued((p, 5), seed=5)  # 5·p elements: pads over p
        xs = jax.device_put(x, comm.sharding(0, 2))
        flat, hier = self._both(
            comm, lambda v: comm.reduce_scatter(v).reshape(1, -1), xs,
            monkeypatch,
        )
        assert flat.tobytes() == hier.tobytes()
        # and the chunks reassemble the padded global sum in rank order
        want = np.zeros(flat.size, np.float32)
        want[:5] = np.asarray(x).sum(0)[:5]
        np.testing.assert_array_equal(flat.reshape(-1), want)

    def test_split_none_and_scalar_payloads(self, comm4, monkeypatch):
        """Replicated (split=None analog) and 0-d payloads go through
        the tiered psum unharmed — the flatten/pad plumbing has no
        shape preconditions."""
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", "2x2")
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        x = jnp.asarray(3.0)

        def kernel(_v):
            return (comm4.psum(x) + 0 * _v.sum()).reshape(1, 1)

        xs = jax.device_put(
            jnp.zeros((4, 1), jnp.float32), comm4.sharding(0, 2)
        )
        out = np.asarray(_run(comm4, kernel, xs))
        np.testing.assert_array_equal(out, 12.0)

    def test_resplit_alltoall_digest_flat_vs_tiered(self, monkeypatch):
        """End-to-end through the planner's a2a program: the tiered
        lowering of a forced-alltoall resplit is bit-identical to the
        flat one (padded, non-divisible extents)."""
        comm = ht.get_comm()
        if comm.size < 4 or comm.size % 2:
            pytest.skip("needs an even mesh >= 4")
        rng = np.random.default_rng(6)
        xn = rng.standard_normal((3 * comm.size + 1, 17)).astype(np.float32)
        monkeypatch.setenv("HEAT_TPU_RELAYOUT_PLAN", "alltoall")
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "0")
        a = ht.array(xn, split=0).resplit(1).numpy()
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        b = ht.array(xn, split=0).resplit(1).numpy()
        assert a.tobytes() == b.tobytes() == xn.tobytes()


# -- HLO audit: per-tier replica groups + zero drift ---------------------------


class TestTieredAudit:
    def _audit(self, comm, kernel, x, ndim=2):
        spec = comm.spec(0, ndim)
        fn = lambda v: jax.shard_map(  # noqa: E731
            kernel, mesh=comm.mesh, in_specs=spec, out_specs=spec
        )(v)
        return hlo.audit_computation(fn, x)

    def test_psum_tier_structure_and_zero_drift(self, comm4, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", "2x2")
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        n = 64
        x = jax.device_put(
            jnp.ones((4, n), jnp.float32), comm4.sharding(0, 2)
        )
        aud = self._audit(comm4, lambda v: comm4.psum(v), x)
        topo = comm4.topology()
        ops = aud.counts()
        assert ops == {"reduce-scatter": 1, "all-reduce": 1, "all-gather": 1}
        by_op = {c.op: c for c in aud.collectives}
        # the emitted replica groups ARE the tier ground truth
        assert [list(g) for g in by_op["reduce-scatter"].groups] == \
            topo.node_groups()
        assert [list(g) for g in by_op["all-reduce"].groups] == \
            topo.cross_groups()
        assert [list(g) for g in by_op["all-gather"].groups] == \
            topo.node_groups()
        pred = model.hierarchical_allreduce_cost(n, 4, topo.node, topo.local)
        rep = hlo.compare(aud, pred)
        assert rep.ok, rep.summary()
        # DCN accounting: the cross-node op's bytes are the dcn_bytes
        assert by_op["all-reduce"].wire_bytes == pred.dcn_bytes

    def test_cross_node_payload_is_the_local_shard(self, comm4, monkeypatch):
        """Acceptance oracle: the cross-node all-reduce moves exactly the
        1/local-sized shard per participant vs the flat ring's full
        payload — and the cross-tier wire-byte reduction is >= local."""
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", "2x2")
        n = 1024
        x = jax.device_put(
            jnp.ones((4, n), jnp.float32), comm4.sharding(0, 2)
        )
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "0")
        flat = self._audit(comm4, lambda v: comm4.psum(v), x)
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        hier = self._audit(comm4, lambda v: comm4.psum(v), x)
        flat_ar = [c for c in flat.collectives if c.op == "all-reduce"]
        cross = [c for c in hier.collectives if c.op == "all-reduce"]
        assert len(flat_ar) == 1 and len(cross) == 1
        topo = comm4.topology()
        assert flat_ar[0].in_bytes == cross[0].in_bytes * topo.local
        reduction = flat_ar[0].wire_bytes / cross[0].wire_bytes
        assert reduction >= topo.local

    @pytest.mark.parametrize("mode", ["int8", "blockwise"])
    def test_cross_precision_shrinks_dcn_bytes(self, comm4, mode,
                                               monkeypatch):
        """×the PR 9 compression factor when a cross-tier precision is
        set: the quantized cross tier is the EQuARX two-phase form on
        int8 payloads, audited zero-drift, while BOTH in-node tiers stay
        exact f32. (bf16 is exempt from the byte assertion on this
        backend: XLA CPU legalizes a summing bf16 all-reduce to f32 —
        the PR 9 caveat — TPU keeps it native.)"""
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", "2x2")
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        n = 1024
        x = jax.device_put(
            jnp.ones((4, n), jnp.float32), comm4.sharding(0, 2)
        )
        comp = self._audit(
            comm4, lambda v: comm4.psum(v, precision=mode), x
        )
        topo = comm4.topology()
        pred = model.hierarchical_allreduce_cost(
            n, 4, topo.node, topo.local, mode
        )
        rep = hlo.compare(comp, pred)
        assert rep.ok, rep.summary()
        # the quantized phases ride the CROSS groups only; both in-node
        # stages (reduce-scatter + final gather) stay exact f32 on the
        # NODE groups
        for c in comp.collectives:
            groups = [list(g) for g in c.groups]
            if c.dtype in ("s8", "u16"):
                assert groups == topo.cross_groups(), c
            else:
                assert c.dtype == "f32"
                if c.op in ("reduce-scatter",):
                    assert groups == topo.node_groups()
        # DCN payload: int8 phases vs the exact f32 cross all-reduce
        exact_pred = model.hierarchical_allreduce_cost(
            n, 4, topo.node, topo.local
        )
        assert pred.dcn_bytes * 3.5 <= exact_pred.dcn_bytes

    def test_gather_and_a2a_zero_drift(self, comm4, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", "2x2")
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        topo = comm4.topology()
        x = jax.device_put(
            jnp.ones((4, 32), jnp.float32), comm4.sharding(0, 2)
        )
        aud = self._audit(
            comm4, lambda v: comm4.all_gather(v)[: v.shape[0]], x
        )
        pred = model.hierarchical_allgather_cost(32, 4, topo.node, topo.local)
        assert hlo.compare(aud, pred).ok
        y = jax.device_put(
            jnp.ones((4, 16), jnp.float32), comm4.sharding(0, 2)
        )
        aud2 = self._audit(
            comm4,
            lambda v: comm4.all_to_all(v, split_axis=1, concat_axis=0), y,
        )
        pred2 = model.hierarchical_a2a_cost(4 * 16, 4, topo.node, topo.local)
        assert hlo.compare(aud2, pred2).ok

    def test_degenerate_topology_lowers_flat(self, comm4, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", "1x4")
        x = jax.device_put(
            jnp.ones((4, 8), jnp.float32), comm4.sharding(0, 2)
        )
        aud = self._audit(comm4, lambda v: comm4.psum(v), x)
        assert aud.counts() == {"all-reduce": 1}


# -- per-tier precision composition bounds -------------------------------------


class TestCrossPrecisionBounds:
    @pytest.mark.parametrize("mode,bound", [
        ("bf16", 2.0 ** -7),
        ("int8", 3 * 1.05 / 127),      # (node+1) quantization steps
        ("blockwise", 3 * 1.05 / 127),
    ])
    def test_psum_error_bound(self, comm4, mode, bound, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", "2x2")
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.standard_normal((4, 256)).astype(np.float32))
        xs = jax.device_put(x, comm4.sharding(0, 2))
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "0")
        exact = np.asarray(_run(comm4, lambda v: comm4.psum(v), xs))
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        got = np.asarray(
            _run(comm4, lambda v: comm4.psum(v, precision=mode), xs)
        )
        err = np.abs(got - exact).max() / np.abs(exact).max()
        assert err <= bound, (mode, err, bound)

    def test_knob_fallback_chain(self, comm4, monkeypatch):
        """HEAT_TPU_HIERARCHICAL_PREC compresses the cross tier without
        touching the flat knob: the tiered program grows the int8
        quantized phases while HEAT_TPU_COLLECTIVE_PREC stays off (and
        the in-node tiers stay exact f32)."""
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", "2x2")
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        monkeypatch.delenv("HEAT_TPU_COLLECTIVE_PREC", raising=False)
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL_PREC", "int8")
        x = jax.device_put(
            jnp.ones((4, 64), jnp.float32), comm4.sharding(0, 2)
        )
        spec = comm4.spec(0, 2)
        fn = lambda v: jax.shard_map(  # noqa: E731
            lambda b: comm4.psum(b), mesh=comm4.mesh,
            in_specs=spec, out_specs=spec,
        )(v)
        aud = hlo.audit_computation(fn, x)
        dtypes = {c.dtype for c in aud.collectives}
        assert "s8" in dtypes  # the quantized cross phases
        rs = [c for c in aud.collectives if c.op == "reduce-scatter"][0]
        assert rs.dtype == "f32"  # in-node tier untouched by the knob


# -- zero-recompile repeat dispatch --------------------------------------------


class TestTieredDispatch:
    def test_repeat_resplit_is_pure_cache_hits(self, monkeypatch):
        comm = ht.get_comm()
        if comm.size < 4 or comm.size % 2:
            pytest.skip("needs an even mesh >= 4")
        from heat_tpu.core import program_cache

        monkeypatch.setenv("HEAT_TPU_RELAYOUT_PLAN", "alltoall")
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        xn = np.arange(float(8 * comm.size * 6), dtype=np.float32).reshape(
            8 * comm.size, 6
        )
        ht.array(xn, split=0).resplit(1).numpy()  # warm
        before = program_cache.stats()
        for _ in range(3):
            ht.array(xn, split=0).resplit(1).numpy()
        after = program_cache.stats()
        assert after["misses"] == before["misses"]
        assert after["hits"] > before["hits"]

    def test_knob_flip_keys_a_fresh_program(self, monkeypatch):
        """program_key carries the topology token: flipping
        HEAT_TPU_HIERARCHICAL must never reuse a stale flat program."""
        from heat_tpu.core import program_cache

        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "0")
        k0 = program_cache.program_key("site", ("cfg",))
        monkeypatch.setenv("HEAT_TPU_HIERARCHICAL", "1")
        k1 = program_cache.program_key("site", ("cfg",))
        if topology.resolve(jax.device_count()).nontrivial:
            assert k0 != k1
        else:
            assert k0 == k1  # trivial topology: tiered == flat


# -- DASO routes through the tier primitives -----------------------------------


@pytest.mark.skipif(
    len(jax.devices()) < 4, reason="DASO 2-level mesh needs >= 4 devices"
)
class TestDasoTieredEquivalence:
    def _legacy_send(self, daso, params):
        """The pre-ISSUE-15 hand-rolled node-group send kernel, inlined
        verbatim — the bit-equivalence oracle for the refactored path."""
        mesh = daso.mesh
        cast = daso.cast_dtype
        n_nodes = daso.n_nodes
        wire = collective_prec.resolve(daso._collective_precision)
        block = collective_prec.block_size()

        def kernel(params):
            params = jax.tree.map(lambda x: x[0], params)

            def one(x):
                rep = jax.lax.pmean(x, "local")
                if wire in ("int8", "blockwise") and (
                    collective_prec.compressible(x.dtype)
                ):
                    return collective_prec.psum(
                        rep, "node", n_nodes, wire, block
                    )[None]
                wire_cast = jnp.bfloat16 if wire == "bf16" else cast
                return jax.lax.psum(rep.astype(wire_cast), "node")[None]

            return jax.tree.map(one, params)

        stacked = P(("node", "local"))

        def send(params):
            specs_p = jax.tree.map(lambda _: stacked, params)
            return jax.shard_map(
                kernel, mesh=mesh, in_specs=(specs_p,), out_specs=specs_p
            )(params)

        return send(params)

    @pytest.mark.parametrize("precision", [None, "bf16", "int8"])
    def test_send_bit_equals_legacy(self, precision):
        import optax

        daso = ht.optim.DASO(
            optax.sgd(0.05), total_epochs=2,
            collective_precision=precision,
        )
        rng = np.random.default_rng(8)
        params = daso.stack_params(
            {"w": jnp.asarray(rng.standard_normal((24, 3)).astype(np.float32))}
        )
        got = daso._get_global_send()(params)
        want = self._legacy_send(daso, params)
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    def test_daso_mesh_comes_from_the_topology_knob(self, monkeypatch):
        import optax

        p = len(jax.devices())
        if p % 4:
            pytest.skip("needs a mesh divisible by 4")
        monkeypatch.setenv("HEAT_TPU_TOPOLOGY", f"{p // 4 * 2}x2")
        daso = ht.optim.DASO(optax.sgd(0.05), total_epochs=2)
        assert daso.n_nodes == p // 4 * 2
        assert daso.mesh.shape == {"node": daso.n_nodes, "local": 2}


# -- cost-model self-consistency -----------------------------------------------


class TestHierarchicalCostModel:
    def test_exact_allgather_total_matches_flat(self):
        # tier split changes, total volume does not (pure movement)
        s, item = 1000, 4
        for node, local in ((2, 2), (2, 4), (4, 2)):
            p = node * local
            h = model.hierarchical_allgather_cost(s, item, node, local)
            assert h.bytes == p * (p - 1) * s * item
            assert 0 < h.dcn_bytes < h.bytes

    def test_allreduce_dcn_accounting(self):
        n, item = 4096, 4
        h24 = model.hierarchical_allreduce_cost(n, item, 2, 4)
        h22 = model.hierarchical_allreduce_cost(n, item, 2, 2)
        h42 = model.hierarchical_allreduce_cost(n, item, 4, 2)
        # total cross wire is 2·B·(node-1): invariant in `local` (each
        # of the `local` groups reduces a 1/local shard), growing with
        # the node count
        assert h24.dcn_bytes == h22.dcn_bytes == 2 * n * item * (2 - 1)
        assert h42.dcn_bytes == 2 * n * item * (4 - 1)
        # the per-DEVICE cross payload is the 1/local shard: flat ring
        # in_bytes / tiered cross in_bytes == local (the audit oracle in
        # TestTieredAudit pins the emitted form of this)
        assert h24.bytes > h22.bytes  # more ICI participants move more

    def test_degenerate_topologies_price_flat(self):
        n, item, p = 512, 4, 8
        flat = model.allreduce_cost(n, item, p)
        for node, local in ((1, 8), (8, 1)):
            h = model.hierarchical_allreduce_cost(n, item, node, local)
            assert (h.kind, h.bytes) == (flat.kind, flat.bytes)
            assert h.dcn_bytes == 0

    def test_weighted_wire_prices_the_premium(self):
        c = model.CollectiveCost("all-reduce", 100, dcn_bytes=40)
        assert model.weighted_wire(c, premium=10.0) == 60 + 400
        flat = model.CollectiveCost("all-reduce", 100)
        assert model.weighted_wire(flat, premium=10.0) == 100.0

    def test_attention_pipeline_now_priced(self):
        """The 6 formerly grandfathered collectives have cost entries."""
        r = model.ring_attention_cost(2, 64, 4, 8, 4, 4)
        assert r.kind == "ppermute-ring" and r.steps == 4 and r.bytes > 0
        u = model.ulysses_attention_cost(2, 64, 4, 8, 4, 4)
        assert u.kind == "all-to-all" and u.bytes == 4 * (2*64*4*8*4) * 3 // 4
        pl = model.pipeline_cost(8, 16, 4, 4, 2)
        assert "ppermute-ring" in pl.kind and "all-reduce" in pl.kind

    def test_ring_attention_audit_matches_cost(self, comm4):
        from heat_tpu.parallel import ring_attention

        b, t, h, d = 1, 16, 2, 4
        rng = np.random.default_rng(9)
        q, k, v = (
            jax.device_put(
                jnp.asarray(rng.standard_normal((b, t, h, d)).astype(
                    np.float32
                )),
                comm4.sharding(1, 4),
            )
            for _ in range(3)
        )
        aud = hlo.audit_computation(
            lambda q, k, v: ring_attention(q, k, v, comm=comm4), q, k, v
        )
        pred = model.ring_attention_cost(b, t, h, d, 4, comm4.size)
        rep = hlo.compare(aud, pred)
        assert rep.ok, rep.summary()
