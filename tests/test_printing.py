"""Printing tests (reference heat/core/printing.py + its tests): __str__
must render the logical global array — never the tail pad — and honor
printoptions."""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import printing


class TestPrinting:
    def test_str_contains_values(self):
        x = ht.arange(5, dtype=ht.int32, split=0)
        s = str(x)
        for v in range(5):
            assert str(v) in s

    def test_str_never_shows_pad(self):
        # 11 over 8 devices pads to 16 — pad values (zeros) must not render
        import re

        x = ht.arange(11, dtype=ht.float32, split=0) + 100.0
        s = str(x)
        data = s.split("]")[0]  # strip the metadata suffix (dtype/split)
        nums = [float(t) for t in re.findall(r"\d+\.?\d*", data)]
        assert len(nums) == 11, s
        assert min(nums) >= 100.0 and max(nums) <= 110.0, s

    def test_repr_equals_str(self):
        x = ht.arange(4, split=0)
        assert repr(x) == str(x)

    def test_2d_render_matches_logical(self):
        xn = np.arange(12, dtype=np.float32).reshape(6, 2)
        x = ht.array(xn, split=0)
        s = str(x)
        assert "11" in s and "0" in s

    def test_scalar_render(self):
        x = ht.array(3.5)
        assert "3.5" in str(x)

    def test_printoptions_roundtrip(self):
        old = printing.get_printoptions()
        try:
            printing.set_printoptions(precision=2)
            assert printing.get_printoptions()["precision"] == 2
            x = ht.array(np.array([1.23456789], dtype=np.float32), split=0)
            assert "1.23456789" not in str(x)
        finally:
            printing.set_printoptions(
                precision=old["precision"],
                threshold=old["threshold"],
                edgeitems=old["edgeitems"],
                linewidth=old["linewidth"],
            )

    def test_large_array_summarizes(self):
        x = ht.arange(10_000, dtype=ht.float32, split=0)
        s = str(x)
        assert "..." in s

    def test_empty_array(self):
        x = ht.array(np.zeros((0,), dtype=np.float32), split=0)
        assert "[]" in str(x).replace(" ", "")
