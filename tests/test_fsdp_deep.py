"""Deep checks of the FSDP/ZeRO placement rule — axis selection, min_size
boundary, indivisible-leaf replication, in-jit constraints, and layout of
real optimizer state (complements tests/test_fsdp.py's value/train-step
checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.parallel import constrain_pytree, replicate_pytree, shard_pytree


@pytest.fixture(scope="module")
def comm():
    return ht.get_comm()


def _sharded_axis(arr):
    """Which axis the NamedSharding splits, or None."""
    spec = arr.sharding.spec
    for i, s in enumerate(spec):
        if s is not None:
            return i
    return None


class TestPlacementRule:
    def test_largest_divisible_axis_wins(self, comm):
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        leaf = jnp.zeros((2 * p, 8 * p))  # both divisible; axis 1 larger
        out = shard_pytree({"w": leaf}, comm, min_size=1)
        assert _sharded_axis(out["w"]) == 1

    def test_indivisible_axes_replicate(self, comm):
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        leaf = jnp.zeros((p + 1, p + 1))
        out = shard_pytree({"w": leaf}, comm, min_size=1)
        assert _sharded_axis(out["w"]) is None

    def test_min_size_boundary(self, comm):
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        small = jnp.zeros((p,))  # size p < min_size -> replicated
        large = jnp.zeros((p * 200,))
        out = shard_pytree({"s": small, "l": large}, comm, min_size=p * 100)
        assert _sharded_axis(out["s"]) is None
        assert _sharded_axis(out["l"]) == 0
        # exactly at the threshold: size == min_size is NOT "smaller" — shards
        exact = jnp.zeros((p * 100,))
        out = shard_pytree({"e": exact}, comm, min_size=p * 100)
        assert _sharded_axis(out["e"]) == 0

    def test_scalar_and_python_leaves(self, comm):
        out = shard_pytree({"step": jnp.asarray(3), "lr": 0.1}, comm)
        assert int(out["step"]) == 3
        assert abs(float(out["lr"]) - 0.1) < 1e-7

    def test_nested_structure_preserved(self, comm):
        tree = {"a": {"b": [jnp.ones((4,)), jnp.ones((2, 2))]}, "c": jnp.ones(())}
        out = shard_pytree(tree, comm)
        assert set(out) == {"a", "c"}
        assert isinstance(out["a"]["b"], list) and len(out["a"]["b"]) == 2


class TestOptimizerStateLayout:
    def test_adam_moments_shard_like_params(self, comm):
        import optax

        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        params = {"w": jnp.ones((4 * p, 8)), "b": jnp.ones((8,))}
        state = optax.adam(1e-3).init(params)
        sp = shard_pytree(params, comm, min_size=1)
        ss = shard_pytree(state, comm, min_size=1)
        mu = ss[0].mu
        # the first moment of w shards along w's biggest divisible axis
        assert _sharded_axis(mu["w"]) == 0
        np.testing.assert_allclose(np.asarray(mu["w"]), 0.0)


class TestConstrainInJit:
    def test_constraint_holds_through_jit(self, comm):
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        x = shard_pytree({"w": jnp.ones((4 * p, 4))}, comm, min_size=1)

        @jax.jit
        def step(t):
            t = {"w": t["w"] * 2.0}
            return constrain_pytree(t, comm, min_size=1)

        out = step(x)
        assert _sharded_axis(out["w"]) == 0
        np.testing.assert_allclose(np.asarray(out["w"]), 2.0)


class TestReplicateRoundtrip:
    def test_values_and_layout(self, comm):
        p = comm.size
        rng = np.random.default_rng(71)
        w = rng.standard_normal((2 * p, 3)).astype(np.float32)
        sh = shard_pytree({"w": jnp.asarray(w)}, comm, min_size=1)
        rep = replicate_pytree(sh, comm)
        assert _sharded_axis(rep["w"]) is None
        np.testing.assert_allclose(np.asarray(rep["w"]), w, rtol=1e-6)
