"""Deep DNDarray container checks — layout metadata on uneven shapes,
lloc local indexing, halo caching/invalidation, redistribute_ target maps,
perf counters, strides, and the __array__ protocol (reference
heat/core/tests/test_dndarray.py, 1,485 LoC — the container-contract
suite)."""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core.dndarray import DNDarray, perf_stats, reset_perf_stats
from .basic_test import TestCase


class TestLayoutMetadata(TestCase):
    def test_uneven_metadata_consistency(self):
        p = self.comm.size
        n = 3 * p + 2
        x = ht.ones((n, 4), split=0)
        assert x.shape == x.gshape == (n, 4)
        assert x.size == x.gnumel == n * 4
        assert x.nbytes == x.gnbytes == n * 4 * 4
        assert x.padded_shape[0] == self.comm.padded_size(n)
        assert x.pad_count == self.comm.padded_size(n) - n
        lmap = x.lshape_map
        assert int(lmap[:, 0].sum()) == n
        assert x.lshape == tuple(lmap[0])

    def test_counts_displs_match_comm(self):
        p = self.comm.size
        n = 2 * p + 1
        x = ht.ones(n, split=0)
        counts, displs = x.counts_displs()
        c2, d2 = self.comm.counts_displs(n)
        assert tuple(counts) == tuple(c2) and tuple(displs) == tuple(d2)

    def test_replicated_has_no_pad(self):
        x = ht.ones((7, 3))
        assert x.split is None and x.pad_count == 0
        assert x.padded_shape == (7, 3)
        assert x.lshape == (7, 3)

    def test_strides_are_local_element_strides(self):
        x = ht.ones((3, 4, 5))
        # replicated: local shard is the full array; element strides C-order
        assert x.strides == (20, 5, 1)
        assert x.stride() == x.strides
        p = self.comm.size
        y = ht.ones((2 * p, 4), split=0)
        rows = y.lshape[0]
        assert y.strides == (4, 1) and rows == 2

    def test_is_distributed(self):
        assert ht.ones(4, split=0).is_distributed() == (self.comm.size > 1)
        assert not ht.ones(4).is_distributed()


class TestLloc(TestCase):
    def test_lloc_reads_local_shard(self):
        p = self.comm.size
        n = 2 * p
        x = ht.arange(n, dtype=ht.float32, split=0)
        local = np.asarray(x.lloc[:])
        # first mesh position's chunk: the leading rows
        np.testing.assert_array_equal(local[: x.lshape[0]], np.arange(x.lshape[0]))

    def test_lloc_write_roundtrip(self):
        x = ht.zeros(2 * self.comm.size, split=0)
        x.lloc[0] = 5.0
        assert float(np.asarray(x.lloc[0])) == 5.0


class TestHaloCache(TestCase):
    def test_halo_props_cached_and_invalidated(self):
        p = self.comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        x = ht.arange(2 * p, dtype=ht.float32, split=0)
        x.get_halo(1)
        hp, hn = x.halo_prev, x.halo_next
        assert hn is not None and hp is not None
        # a setitem mutates the buffer → cached halos must be dropped
        x[0] = 99.0
        assert x.halo_prev is None and x.halo_next is None

    def test_halo_rejects_oversized(self):
        p = self.comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        x = ht.arange(2 * p, dtype=ht.float32, split=0)
        with pytest.raises(ValueError):
            x.get_halo(3 * p)

    def test_halo_noop_on_replicated(self):
        x = ht.arange(6, dtype=ht.float32)
        x.get_halo(1)
        assert x.halo_prev is None and x.halo_next is None


class TestRedistribute(TestCase):
    def test_target_map_roundtrip(self):
        p = self.comm.size
        n = 4 * p
        a = np.arange(n, dtype=np.float32)
        x = ht.array(a, split=0)
        target = x.lshape_map.copy()
        x.redistribute_(target_map=target)  # identity target: values intact
        self.assert_array_equal(x, a)

    def test_ragged_target_map_formally_closed(self):
        # PARITY.md "redistribute_ and ragged target maps": non-canonical
        # targets raise, naming the supported relayouts
        p = self.comm.size
        if p < 2:
            pytest.skip("needs >= 2 devices for a ragged map")
        a = np.arange(4 * p, dtype=np.float32)
        x = ht.array(a, split=0)
        ragged = x.lshape_map.copy()
        ragged[0, 0] += 1
        ragged[1, 0] -= 1
        with pytest.raises(NotImplementedError, match="resplit_"):
            x.redistribute_(target_map=ragged)
        self.assert_array_equal(x, a)  # untouched after the refusal

    def test_balance_on_balanced_noop(self):
        a = np.arange(3 * self.comm.size + 1, dtype=np.float32)
        x = ht.array(a, split=0)
        assert x.is_balanced(force_check=True)
        x.balance_()
        self.assert_array_equal(x, a)

    def test_resplit_method_returns_new(self):
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        x = ht.array(a, split=0)
        y = x.resplit(1)
        assert y.split == 1 and x.split == 0
        self.assert_array_equal(y, a)
        self.assert_array_equal(x, a)


class TestArrayProtocol(TestCase):
    def test_array_protocol_and_dtype_arg(self):
        a = np.arange(4, dtype=np.float32)
        x = ht.array(a, split=0)
        np.testing.assert_array_equal(np.asarray(x), a)
        got = np.asarray(x, dtype=np.int64)
        assert got.dtype == np.int64

    def test_numpy_matches_logical(self):
        p = self.comm.size
        a = np.arange(p + 1, dtype=np.float32)
        x = ht.array(a, split=0)  # padded physically
        np.testing.assert_array_equal(x.numpy(), a)
        assert x.numpy().shape == (p + 1,)

    def test_mixed_numpy_binary_returns_dndarray_on_left(self):
        a = np.ones(3, dtype=np.float32)
        x = ht.ones(3, split=0)
        out = x + a
        assert isinstance(out, ht.DNDarray)
        self.assert_array_equal(out, 2 * a)


class TestPerfCounters(TestCase):
    def test_relayout_advances_counters_then_reset(self):
        p = self.comm.size
        if p < 2:
            pytest.skip("1-device resplit is a no-op — nothing to count")
        reset_perf_stats()
        # an uneven resplit must go through the logical view: at least one
        # pad-slice or re-pad or device_put is mandatory
        x = ht.arange(p + 1, dtype=ht.float32, split=0)
        _ = ht.resplit(x, None)
        stats = perf_stats()
        assert sum(stats.values()) > 0, stats
        reset_perf_stats()
        cleared = perf_stats()
        assert set(cleared) == {"logical_slices", "repads", "device_puts"}
        assert all(v == 0 for v in cleared.values())

    def test_physical_chain_leaves_counters_at_zero(self):
        p = self.comm.size
        x = ht.arange((p + 1) * 2, dtype=ht.float32, split=0).reshape((p + 1, 2))
        reset_perf_stats()
        # pad-safe ops: flip/roll off-split + elementwise stay physical
        y = ht.flip(x, 1)
        y = ht.roll(y, 1, axis=1)
        y = y + 1.0
        stats = perf_stats()
        assert sum(stats.values()) == 0, stats


class TestDeviceMoves(TestCase):
    def test_cpu_returns_dndarray(self):
        x = ht.ones(4, split=0)
        y = x.cpu()
        assert isinstance(y, ht.DNDarray)
        self.assert_array_equal(y, np.ones(4))

    def test_astype_copy_false_same_dtype(self):
        x = ht.ones(4, dtype=ht.float32)
        y = x.astype(ht.float32, copy=False)
        assert y.dtype == ht.float32


class TestFromLogical(TestCase):
    def test_from_logical_pads_correctly(self):
        import jax.numpy as jnp

        p = self.comm.size
        n = p + 1
        log = jnp.arange(n, dtype=jnp.float32)
        x = DNDarray.from_logical(log, 0, ht.get_device(), self.comm)
        assert tuple(x.shape) == (n,)
        assert x.larray.shape[0] == self.comm.padded_size(n)
        self.assert_array_equal(x, np.arange(n, dtype=np.float32))

    def test_from_logical_replicated(self):
        import jax.numpy as jnp

        log = jnp.ones((2, 3), dtype=jnp.float32)
        x = DNDarray.from_logical(log, None, ht.get_device(), self.comm)
        assert x.split is None and x.pad_count == 0


class TestFillDiagonalPhysical(TestCase):
    """fill_diagonal writes the shard-local diagonal positions via a masked
    where on the physical buffer — no gather, any split, any rectangle."""

    def test_grid_no_gather(self):
        from heat_tpu.core.dndarray import _PERF_STATS

        rng = np.random.default_rng(151)
        n = 3 * self.comm.size + 1
        for shape in ((n, n), (n, 4), (4, n)):
            for split in (None, 0, 1):
                t = rng.standard_normal(shape).astype(np.float32)
                x = ht.array(t, split=split)
                c0 = _PERF_STATS["logical_slices"]
                r = x.fill_diagonal(-2.5)
                assert r is x
                assert _PERF_STATS["logical_slices"] == c0
                w = t.copy()
                np.fill_diagonal(w, -2.5)
                np.testing.assert_array_equal(x.numpy(), w)
