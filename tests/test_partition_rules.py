"""Partition-rule engine tests (ISSUE 18 satellite): regex precedence,
the replicated default for unmatched leaves, repr round-trip, and rule
resolution over nested dict/list/custom-node pytrees."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.parallel import fsdp as F
from heat_tpu.parallel import (
    FsdpPlan,
    PartitionRules,
    fsdp_shard,
    fsdp_unshard,
    leaf_paths,
    plan_partition,
)


@pytest.fixture(scope="module")
def comm():
    return ht.get_comm()


class TestRuleMatching:
    def test_first_match_wins(self):
        rules = PartitionRules((
            ("attn/(query|key|value)", "fsdp", "bf16"),
            (r"attn/.*", "replicate"),
            (".*", "fsdp"),
        ))
        # rule 0 and rule 1 both match; precedence is ORDER, not specificity
        assert rules.match("block0/attn/query/kernel") == ("fsdp", "bf16", 0)
        assert rules.match("block0/attn/out/kernel") == ("replicate", None, 1)
        assert rules.match("lm_head/kernel") == ("fsdp", None, 2)

    def test_search_semantics_not_fullmatch(self):
        # re.search: the pattern may hit anywhere in the path
        rules = PartitionRules((("bias", "replicate"), (".*", "fsdp")))
        assert rules.match("deep/nested/bias")[0] == "replicate"
        assert rules.match("bias_correction")[0] == "replicate"
        assert rules.match(r"kernel")[0] == "fsdp"

    def test_unmatched_leaf_replicates(self):
        # deliberate divergence from the exemplar (which raises): a partial
        # rule table must be safe on models it was not written for
        rules = PartitionRules((("attn/", "fsdp"),))
        assert rules.match("mlp/kernel") == ("replicate", None, -1)
        assert PartitionRules(()).match("anything") == ("replicate", None, -1)

    def test_anchored_patterns(self):
        rules = PartitionRules(((r"^embed/", "replicate"), (".*", "fsdp")))
        assert rules.match("embed/table")[0] == "replicate"
        assert rules.match("block0/embed/kernel")[0] == "fsdp"

    def test_bad_rules_rejected(self):
        with pytest.raises(re.error):
            PartitionRules((("([unclosed", "fsdp"),))
        with pytest.raises(ValueError):
            PartitionRules(((".*", "sharded"),))  # not a placement
        with pytest.raises(ValueError):
            PartitionRules(((".*", "fsdp", "fp8"),))  # not a wire mode
        with pytest.raises(ValueError):
            PartitionRules(((".*",),))  # arity


class TestReprRoundTrip:
    def test_repr_parses_back(self):
        rules = PartitionRules((
            ("attn/(query|key|value)", "fsdp", "bf16"),
            (r"bias$", "replicate"),
            (".*", "fsdp", "off"),
        ))
        again = PartitionRules.parse(repr(rules))
        assert again == rules
        assert hash(again) == hash(rules)

    def test_parse_bare_tuple_literal(self):
        rules = PartitionRules.parse("(('kernel', 'fsdp'),)")
        assert rules.match("a/kernel")[0] == "fsdp"

    def test_eq_is_structural(self):
        a = PartitionRules(((".*", "fsdp"),))
        b = PartitionRules([[".*", "fsdp"]])
        assert a == b
        assert a != PartitionRules(((".*", "replicate"),))
        assert a != "PartitionRules"


class TestLeafPaths:
    def test_nested_dict_list_paths(self):
        tree = {
            "block": {"attn": {"q": jnp.zeros((2, 2))}},
            "head": [jnp.zeros((3,)), jnp.zeros(())],
        }
        paths = [p for p, _ in leaf_paths(tree)]
        assert paths == ["block/attn/q", "head/0", "head/1"]

    def test_custom_node_paths(self):
        # flax FrozenDict is a registered custom pytree node
        from flax.core import freeze

        tree = freeze({"layer": {"kernel": jnp.zeros((4, 4))}})
        paths = [p for p, _ in leaf_paths(tree)]
        assert paths == ["layer/kernel"]

    def test_tuple_of_stage_trees(self):
        tree = ({"w": jnp.zeros((2,))}, {"w": jnp.zeros((2,))})
        paths = [p for p, _ in leaf_paths(tree)]
        assert paths == ["0/w", "1/w"]


class TestPlanPartition:
    def test_scalars_always_replicate(self, comm):
        plan = plan_partition(
            {"w": jnp.ones((comm.size * 2,)), "step": jnp.float32(0.0)},
            PartitionRules.fsdp_default(), comm,
        )
        by = plan.by_path
        assert by["w"].sharded and by["w"].chunk == 2
        assert not by["step"].sharded and by["step"].chunk == 0

    def test_plan_signature_hashable_and_layout_sensitive(self, comm):
        t1 = {"w": jnp.ones((8, 8))}
        plan_a = plan_partition(t1, PartitionRules.fsdp_default(), comm)
        plan_b = plan_partition(
            t1, PartitionRules(((".*", "replicate"),)), comm
        )
        assert hash(plan_a.signature()) != hash(plan_b.signature())
        assert isinstance(plan_a, FsdpPlan)

    def test_rule_wire_and_env_fallback(self, comm, monkeypatch):
        monkeypatch.delenv("HEAT_TPU_FSDP_PREC", raising=False)
        tree = {"a": jnp.ones((16,)), "b": jnp.ones((16,))}
        rules = PartitionRules((("a", "fsdp", "int8"), (".*", "fsdp")))
        plan = plan_partition(tree, rules, comm)
        assert plan.by_path["a"].wire == "int8"   # per-rule pin wins
        assert plan.by_path["b"].wire == "off"    # flat default stays exact
        monkeypatch.setenv("HEAT_TPU_FSDP_PREC", "bf16")
        plan2 = plan_partition(tree, rules, comm)
        assert plan2.by_path["a"].wire == "int8"
        assert plan2.by_path["b"].wire == "bf16"

    def test_nonfloat_leaf_wire_demotes_to_off(self, comm):
        rules = PartitionRules(((".*", "fsdp", "int8"),))
        plan = plan_partition({"idx": jnp.zeros((16,), jnp.int32)}, rules, comm)
        assert plan.by_path["idx"].wire == "off"

    def test_blockwise_chunk_rounds_to_blocks(self, comm):
        rules = PartitionRules(((".*", "fsdp", "blockwise"),))
        plan = plan_partition({"w": jnp.ones((1000,))}, rules, comm)
        lp = plan.by_path["w"]
        assert lp.chunk == F.flat_chunk(1000, comm.size, "blockwise")

    def test_ambiguous_replicated_row_shape_rejected(self, comm):
        p = comm.size
        # sharded leaf of 4p elements -> (p, 4) rows; a REPLICATED leaf of
        # logical shape (p, 4) is indistinguishable by shape
        tree = {"w": jnp.ones((4 * p,)), "trap": jnp.ones((p, 4))}
        rules = PartitionRules((("w", "fsdp"), ("trap", "replicate")))
        with pytest.raises(ValueError, match="ambiguous partition plan"):
            plan_partition(tree, rules, comm)

    def test_unmatched_default_replicates_in_plan(self, comm):
        plan = plan_partition(
            {"w": jnp.ones((16,))}, PartitionRules((("zzz", "fsdp"),)), comm
        )
        assert not plan.by_path["w"].sharded


class TestShardUnshard:
    def test_roundtrip_mixed_tree(self, comm):
        p = comm.size
        tree = {
            "big": jnp.arange(p * 3 + 1, dtype=jnp.float32),  # uneven: pads
            "rep": jnp.ones((3, 5)),
            "s": jnp.float32(7.0),
        }
        rules = PartitionRules((("big", "fsdp"),))
        plan = plan_partition(tree, rules, comm)
        sharded = fsdp_shard(tree, plan, comm)
        assert sharded["big"].shape == (p, plan.by_path["big"].chunk)
        logical = fsdp_unshard(sharded, plan)
        for (path, a), (_, b) in zip(leaf_paths(tree), leaf_paths(logical)):
            assert np.array_equal(np.asarray(a), np.asarray(b)), path

    def test_shape_mismatch_rejected(self, comm):
        tree = {"w": jnp.ones((16,))}
        plan = plan_partition(tree, PartitionRules.fsdp_default(), comm)
        with pytest.raises(ValueError, match="re-plan"):
            fsdp_shard({"w": jnp.ones((8,))}, plan, comm)
