"""Smoke of the scaling-benchmark harness (benchmarks/ — the reference's
per-algorithm config + runner + jobscript-generator tree,
benchmarks/kmeans/config.json:1-73, generate_jobscripts.py:12-50).
Runners execute in subprocesses at tiny sizes on a forced 2-device mesh;
the generator's sweep enumeration is checked in-process."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep subprocesses off a (possibly
    # wedged) accelerator tunnel — the harness must work CPU-only
    return subprocess.run(
        cmd, capture_output=True, text=True, timeout=600, cwd=REPO, env=env
    )


class TestGenerator:
    def test_enumerates_every_config(self, tmp_path):
        out = tmp_path / "runs.sh"
        r = _run([sys.executable, "benchmarks/generate_runs.py",
                  "--out", str(out)])
        assert r.returncode == 0, r.stderr[-500:]
        text = out.read_text()
        for algo in ("kmeans", "distance_matrix", "statistical_moments",
                     "lasso"):
            assert f"benchmarks/{algo}/heat_tpu.py" in text
        # strong AND weak points for every mesh entry
        assert text.count("strong") and text.count("weak")

    def test_rejects_unknown_algo(self):
        r = _run([sys.executable, "benchmarks/generate_runs.py",
                  "--algos", "nope"])
        assert r.returncode != 0


@pytest.mark.parametrize(
    "runner,extra",
    [
        ("kmeans", ["--clusters", "3", "--iterations", "3"]),
        ("distance_matrix", []),
        ("distance_matrix", ["--ring"]),
        ("statistical_moments", []),
        ("lasso", ["--sweeps", "3"]),
    ],
)
def test_runner_smoke(runner, extra):
    r = _run([
        sys.executable, f"benchmarks/{runner}/heat_tpu.py",
        "--n", "4000", "--features", "8", "--trials", "2", "--mesh", "2",
        *extra,
    ])
    assert r.returncode == 0, r.stderr[-800:]
    lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
    assert any("compile_seconds" in l for l in lines)
    summary = lines[-1]
    assert summary["trials"] == 2 and summary["best_seconds"] > 0
    assert summary["devices"]["count"] == 2


@pytest.mark.slow
def test_serving_runner_smoke():
    """The serving loadgen runner (ISSUE 8): zero registry misses during
    the load window, no failures, the bench-honesty pair on the summary.
    Slow-marked (fresh-process jax import + fit + load, ~12s); the CI
    serving gate exercises the same runner end to end every sweep."""
    r = _run([
        sys.executable, "benchmarks/serving/heat_tpu.py",
        "--n", "512", "--features", "8", "--mesh", "2",
        "--requests", "40", "--rate", "400", "--max-batch", "4",
        "--endpoints", "kmeans,dense", "--digest",
    ])
    assert r.returncode == 0, r.stderr[-800:]
    lines = [json.loads(l) for l in r.stdout.strip().splitlines()]
    cmp_ = next(l["serving_compare"] for l in lines
                if "serving_compare" in l)
    assert cmp_["misses_during_load"] == 0
    assert cmp_["failed"] == 0 and cmp_["shed"] == 0
    assert cmp_["post_ok"] is True
    assert len(cmp_["digest"]) == 64
    summary = next(l for l in lines if l.get("bench") == "serving")
    assert summary["on_chip"] is False
    assert isinstance(summary["cpu_fallback"], str)
    assert summary["achieved_qps"] > 0
