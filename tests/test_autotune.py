"""heat_tpu.autotune (ISSUE 11): search space from the knob registry,
analytic pruning ordered by the collective cost model, measured trials
that never pick worse than default, error-budget refusal of lossy modes,
DB round-trip + foreign-record rejection, second-process zero-trial warm
start, and the default-off dispatch guarantee."""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import _knobs as knobs
from heat_tpu import autotune as at
from heat_tpu import telemetry as tm
from heat_tpu.autotune import cost, db, space, trials
from heat_tpu.core import collective_prec
from heat_tpu.core import program_cache as pc
from heat_tpu.telemetry import collectives as cost_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SEARCH_PLAN = ["HEAT_TPU_RELAYOUT_PLAN"]
SEARCH_PREC = ["HEAT_TPU_COLLECTIVE_PREC"]


@pytest.fixture(autouse=True)
def _clean():
    at.reset()
    knobs.clear_overrides()
    yield
    at.reset()
    knobs.clear_overrides()
    tm.disable()
    tm.get_registry().clear()


def _resplit_workload(n=256, f=32, seed=0):
    rng = np.random.default_rng(seed)
    x = ht.array(rng.standard_normal((n, f)).astype(np.float32), split=0)
    return x, (lambda: x.resplit(1).larray)


# -- knob overlay (the adoption mechanism) ------------------------------------


class TestKnobOverlay:
    def test_override_wins_over_env_and_restores(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_FUSION_DEPTH", "32")
        assert knobs.get("HEAT_TPU_FUSION_DEPTH") == 32
        with knobs.overlay({"HEAT_TPU_FUSION_DEPTH": "8"}):
            assert knobs.get("HEAT_TPU_FUSION_DEPTH") == 8
            assert knobs.raw("HEAT_TPU_FUSION_DEPTH") == "8"
        assert knobs.get("HEAT_TPU_FUSION_DEPTH") == 32

    def test_overlay_nests_and_restores_absence(self):
        assert knobs.raw("HEAT_TPU_RELAYOUT_PLAN") is None
        with knobs.overlay({"HEAT_TPU_RELAYOUT_PLAN": "chunked"}):
            with knobs.overlay({"HEAT_TPU_RELAYOUT_PLAN": "alltoall"}):
                assert knobs.get("HEAT_TPU_RELAYOUT_PLAN") == "alltoall"
            assert knobs.get("HEAT_TPU_RELAYOUT_PLAN") == "chunked"
        assert knobs.raw("HEAT_TPU_RELAYOUT_PLAN") is None

    def test_unregistered_override_rejected(self):
        with pytest.raises(KeyError):
            knobs.set_override("HEAT_TPU_NOT_A_KNOB", "1")

    def test_every_consumer_sees_tuned_values(self):
        """The overlay rides the registry's one read choke point, so the
        modules that parse knobs themselves see tuned values live."""
        from heat_tpu.core import fusion, relayout_planner

        with knobs.overlay({
            "HEAT_TPU_RELAYOUT_PLAN": "monolithic",
            "HEAT_TPU_FUSION_DEPTH": "4",
            "HEAT_TPU_COLLECTIVE_PREC": "bf16",
        }):
            assert relayout_planner.mode() == "monolithic"
            assert fusion.depth_cap() == 4
            assert collective_prec.mode() == "bf16"


# -- tunable metadata (search space declared next to the knob) ----------------


class TestTunableMetadata:
    def test_declared_search_spaces_are_sane(self):
        tun = knobs.tunables()
        assert len(tun) >= 12
        for name, k in tun.items():
            t = k.tunable
            assert t.kind in ("exact", "lossy", "neutral"), name
            assert t.values and all(
                isinstance(v, str) and v for v in t.values
            ), name
            if t.kind == "lossy":
                assert t.exact_value in t.values, name
            if k.type == "enum":
                assert set(t.values) <= set(k.choices), name

    def test_lossy_classes_cover_the_accuracy_frontier_knobs(self):
        for name in ("HEAT_TPU_COLLECTIVE_PREC", "HEAT_TPU_CDIST_PREC",
                     "HEAT_TPU_SERVE_EXACT"):
            assert knobs.REGISTRY[name].tunable.kind == "lossy", name
        for name in ("HEAT_TPU_RELAYOUT_PLAN", "HEAT_TPU_FUSION_DEPTH",
                     "HEAT_TPU_RING_OVERLAP"):
            assert knobs.REGISTRY[name].tunable.kind == "exact", name

    def test_autotune_knobs_registered(self):
        for name in ("HEAT_TPU_AUTOTUNE", "HEAT_TPU_TUNE_DB",
                     "HEAT_TPU_AUTOTUNE_TRIALS", "HEAT_TPU_AUTOTUNE_BUDGET",
                     "HEAT_TPU_CI_SKIP_AUTOTUNE"):
            assert name in knobs.REGISTRY, name
        assert knobs.get("HEAT_TPU_AUTOTUNE") is False  # default-off


# -- candidate lattice --------------------------------------------------------


class TestSpace:
    def test_default_config_is_candidate_zero(self):
        cfgs = space.candidates(SEARCH_PLAN)
        assert cfgs[0] == {"HEAT_TPU_RELAYOUT_PLAN": "auto"}
        assert len(cfgs) == 4

    def test_lossy_pinned_without_budget(self):
        cfgs = space.candidates(SEARCH_PLAN + SEARCH_PREC)
        assert all(
            c["HEAT_TPU_COLLECTIVE_PREC"] == "off" for c in cfgs
        )
        cfgs = space.candidates(
            SEARCH_PLAN + SEARCH_PREC, error_budget=0.01
        )
        assert {c["HEAT_TPU_COLLECTIVE_PREC"] for c in cfgs} == {
            "off", "bf16", "int8", "blockwise"
        }

    def test_env_value_joins_the_lattice(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_FUSION_DEPTH", "12")
        cfgs = space.candidates(["HEAT_TPU_FUSION_DEPTH"])
        assert cfgs[0] == {"HEAT_TPU_FUSION_DEPTH": "12"}
        assert {c["HEAT_TPU_FUSION_DEPTH"] for c in cfgs} == {
            "12", "4", "8", "16", "32", "64"
        }

    def test_exact_variant_and_lossy_shift(self):
        base = space.default_config(SEARCH_PREC + SEARCH_PLAN)
        assert space.exact_variant(base)["HEAT_TPU_COLLECTIVE_PREC"] == "off"
        shifted = dict(base, HEAT_TPU_COLLECTIVE_PREC="int8")
        assert space.is_lossy_shift(shifted, base)
        exact_shift = dict(base, HEAT_TPU_RELAYOUT_PLAN="chunked")
        assert not space.is_lossy_shift(exact_shift, base)

    def test_untunable_knob_rejected(self):
        with pytest.raises(ValueError, match="tunable"):
            space.candidates(["HEAT_TPU_TELEMETRY"])


# -- analytic pruning ---------------------------------------------------------


class TestCostPruning:
    def test_pruning_order_matches_the_analytic_model(self):
        """The offline rank over precision modes must be EXACTLY the
        collective cost model's byte ordering for the same signature."""
        gshape, itemsize, p = (4096, 256), 4, 4
        fn = cost.relayout_cost_fn(gshape, itemsize, 0, 1, p)
        cfgs = [
            {"HEAT_TPU_RELAYOUT_PLAN": "alltoall",
             "HEAT_TPU_COLLECTIVE_PREC": m}
            for m in ("off", "bf16", "int8", "blockwise")
        ]
        ranked = cost.rank(cfgs, fn)
        got = [cfg["HEAT_TPU_COLLECTIVE_PREC"] for _, _, cfg in ranked]
        expected = sorted(
            ("off", "bf16", "int8", "blockwise"),
            key=lambda m: cost_model.relayout_cost(
                gshape, itemsize, 0, 1, p, precision=m
            ).bytes,
        )
        assert got == expected
        # and the predicted numbers ARE the model's numbers
        for c, _, cfg in ranked:
            m = cfg["HEAT_TPU_COLLECTIVE_PREC"]
            assert c == cost_model.relayout_cost(
                gshape, itemsize, 0, 1, p, precision=m
            ).bytes

    def test_prune_always_keeps_default_first(self):
        fn = cost.relayout_cost_fn((4096, 256), 4, 0, 1, 4)
        cfgs = space.candidates(SEARCH_PREC + SEARCH_PLAN,
                                error_budget=0.01)
        kept = cost.prune(cfgs, fn, keep=3)
        assert kept[0] == cfgs[0]
        assert len(kept) == 3

    def test_temp_model_marks_infeasible(self):
        """A budget below even the chunked temp need prices to inf —
        the memory_analysis-calibrated feasibility gate."""
        fn = cost.relayout_cost_fn((4096, 256), 4, 0, 1, 4, budget=1)
        c = fn({"HEAT_TPU_RELAYOUT_PLAN": "monolithic",
                "HEAT_TPU_COLLECTIVE_PREC": "off"})
        assert c == float("inf")

    def test_no_model_measures_everything(self):
        cfgs = space.candidates(SEARCH_PLAN)
        assert cost.prune(cfgs, None, keep=2) == cfgs


# -- trial machinery ----------------------------------------------------------


class TestTrials:
    def test_robust_median_rejects_outliers(self):
        assert trials.robust_median([1.0, 1.01, 0.99, 1.0, 50.0]) == 1.0
        assert trials.robust_median([2.0]) == 2.0

    def test_digest_is_bit_and_dtype_exact(self):
        a = np.arange(6, dtype=np.float32)
        assert trials.digest(a) == trials.digest(a.copy())
        assert trials.digest(a) != trials.digest(a.astype(np.float64))
        assert trials.digest(a) != trials.digest(a.reshape(2, 3))
        b = a.copy()
        b[3] = np.nextafter(b[3], np.inf)
        assert trials.digest(a) != trials.digest(b)

    def test_max_rel_err(self):
        ref = np.array([0.0, 2.0, -4.0])
        out = ref + np.array([0.0, 0.0, 0.04])
        assert trials.max_rel_err(out, ref) == pytest.approx(0.01)
        assert trials.max_rel_err(np.zeros(2), np.zeros(3)) == float("inf")


# -- persistent tuning DB -----------------------------------------------------


class TestTuneDB:
    def _record(self, key, site="resplit", mesh=None):
        return {
            "schema": db.SCHEMA, "key": key, "site": site,
            "signature": "sig", "mesh": mesh or db.mesh_fingerprint(),
            "config": {"HEAT_TPU_RELAYOUT_PLAN": "alltoall"},
            "baseline_wall": 1.0, "tuned_wall": 0.5, "created": 0.0,
        }

    def test_key_is_stable_and_signature_sensitive(self):
        mesh = db.mesh_fingerprint()
        k1 = db.tune_key("resplit", ((256, 32), 0, 1), mesh)
        assert k1 == db.tune_key("resplit", ((256, 32), 0, 1), mesh)
        assert k1 != db.tune_key("resplit", ((256, 33), 0, 1), mesh)
        other = dict(mesh, devices=mesh["devices"] + 1)
        assert k1 != db.tune_key("resplit", ((256, 32), 0, 1), other)

    def test_round_trip(self, tmp_path):
        d = db.TuneDB(str(tmp_path / "db"))
        key = db.tune_key("resplit", "sig")
        path = d.store(self._record(key))
        assert os.path.basename(path) == f"{key}.json"
        rec = d.lookup(key)
        assert rec is not None and rec["site"] == "resplit"
        assert [r["key"] for r in d.records()] == [key]

    def test_corrupt_record_cleanly_rejected(self, tmp_path):
        d = db.TuneDB(str(tmp_path / "db"))
        os.makedirs(d.path)  # the dir is otherwise created on first store
        key = db.tune_key("resplit", "sig")
        with open(os.path.join(d.path, f"{key}.json"), "w") as f:
            f.write('{"schema": 1, "key": TRUNCATED')
        assert d.lookup(key) is None
        assert list(d.records()) == []

    def test_foreign_records_cleanly_rejected(self, tmp_path):
        d = db.TuneDB(str(tmp_path / "db"))
        os.makedirs(d.path)  # the dir is otherwise created on first store
        mesh = db.mesh_fingerprint()
        # wrong mesh topology
        foreign = dict(mesh, devices=mesh["devices"] + 1)
        key = db.tune_key("resplit", "sig", foreign)
        rec = self._record(key, mesh=foreign)
        with open(os.path.join(d.path, f"{key}.json"), "w") as f:
            json.dump(rec, f)
        assert d.lookup(key) is None
        # schema drift
        key2 = db.tune_key("reduce", "sig")
        rec2 = dict(self._record(key2, site="reduce"), schema=db.SCHEMA + 1)
        with open(os.path.join(d.path, f"{key2}.json"), "w") as f:
            json.dump(rec2, f)
        assert d.lookup(key2) is None
        # key/filename mismatch (a renamed record is foreign)
        key3 = db.tune_key("serve", "sig")
        with open(os.path.join(d.path, f"{key3}.json"), "w") as f:
            json.dump(self._record(key), f)
        assert d.lookup(key3) is None
        assert list(d.records()) == []

    def test_store_refuses_unregistered_config_knobs(self, tmp_path):
        d = db.TuneDB(str(tmp_path / "db"))
        key = db.tune_key("resplit", "sig")
        rec = self._record(key)
        rec["config"] = {"HEAT_TPU_NOT_A_KNOB": "1"}
        with pytest.raises(ValueError, match="invalid tuning record"):
            d.store(rec)

    def test_open_db_env(self, tmp_path, monkeypatch):
        assert db.open_db() is None or os.environ.get("HEAT_TPU_TUNE_DB")
        monkeypatch.setenv("HEAT_TPU_TUNE_DB", str(tmp_path / "envdb"))
        d = db.open_db()
        assert d is not None and d.path == str(tmp_path / "envdb")


# -- the tuner ----------------------------------------------------------------


class TestTune:
    def test_winner_never_worse_than_default(self, tmp_path):
        """The default config is measured under the same protocol as
        every challenger and wins ties, so tuned_wall <= baseline_wall
        by construction."""
        x, work = _resplit_workload()
        res = at.tune(
            "resplit", work, signature=("r", x.shape, 0, 1),
            search=SEARCH_PLAN, trials_per_config=2,
            db_dir=str(tmp_path / "db"),
            cost_fn=cost.relayout_cost_fn(x.shape, 4, 0, 1,
                                          ht.get_comm().size),
        )
        assert not res.from_db and res.trials_run > 0
        rec = res.record
        assert rec["tuned_wall"] <= rec["baseline_wall"]
        assert rec["validation"] == "digest" and rec["max_rel_err"] == 0.0
        # the winner is adopted into the overlay
        assert at.adopted()["resplit"] == res.config

    def test_db_hit_skips_trials_and_adopts(self, tmp_path):
        x, work = _resplit_workload()
        kwargs = dict(
            signature=("r", x.shape, 0, 1), search=SEARCH_PLAN,
            trials_per_config=2, db_dir=str(tmp_path / "db"),
        )
        first = at.tune("resplit", work, **kwargs)
        at.reset()
        second = at.tune("resplit", work, **kwargs)
        assert second.from_db and second.trials_run == 0
        assert second.config == first.config
        assert at.adopted()["resplit"] == first.config

    def test_db_hit_respects_callers_tighter_budget(self, tmp_path):
        """A persisted LOSSY winner is only a hit when the current
        caller's budget covers its measured error: a tighter budget (or
        none at all — exact-only) discards the hit and re-tunes, so a
        record tuned under a loose budget can never violate a later
        caller's stated contract. The lossy record is planted directly
        so the gate is exercised regardless of which mode wins the
        measured race on this host."""
        budget = 1.05 / 127
        x, work = _resplit_workload()
        sig = ("rh", x.shape, 0, 1)
        mesh = db.mesh_fingerprint()
        key = db.tune_key("resplit", sig, mesh)
        d = db.TuneDB(str(tmp_path / "db"))
        d.store({
            "schema": db.SCHEMA, "key": key, "site": "resplit",
            "signature": repr(sig), "mesh": mesh,
            "config": {"HEAT_TPU_COLLECTIVE_PREC": "int8"},
            "default_config": {"HEAT_TPU_COLLECTIVE_PREC": "off"},
            "baseline_wall": 1.0, "tuned_wall": 0.5, "speedup": 2.0,
            "trials": 4, "configs_measured": 2, "lattice": 4,
            "error_budget": budget, "max_rel_err": 0.004,
            "validation": "allclose", "created": 0.0,
        })
        kwargs = dict(signature=sig, search=SEARCH_PREC,
                      trials_per_config=2, db_dir=d.path)
        # a budget covering the record's measured error hits: zero trials
        first = at.tune("resplit", work, error_budget=budget, **kwargs)
        assert first.from_db and first.trials_run == 0
        assert first.config == {"HEAT_TPU_COLLECTIVE_PREC": "int8"}
        at.reset()
        # tighter budget: must NOT warm-start — re-tunes under it
        # (persist=False keeps the lossy record in place for the probes)
        second = at.tune("resplit", work, error_budget=1e-12,
                         persist=False, **kwargs)
        assert not second.from_db and second.trials_run > 0
        assert second.record["validation"] == "digest"
        at.reset()
        # no budget at all (exact-only caller): same refusal
        third = at.tune("resplit", work, persist=False, **kwargs)
        assert not third.from_db
        assert third.record["validation"] == "digest"

    def test_unopenable_db_degrades_to_in_memory_tuning(self, tmp_path):
        """An unopenable HEAT_TPU_TUNE_DB (a path component is a plain
        file) degrades to in-memory tuning — the winner is measured and
        adopted, never a crash (db.py contract, same as warm_start)."""
        blocker = tmp_path / "blocker"
        blocker.write_text("not a directory")
        x, work = _resplit_workload()
        res = at.tune(
            "resplit", work, signature=("ro", x.shape, 0, 1),
            search=SEARCH_PLAN, trials_per_config=2,
            db_dir=str(blocker / "db"),
        )
        assert not res.from_db and res.trials_run > 0
        assert at.adopted()["resplit"] == res.config

    def test_concurrent_tunes_serialize_on_the_module_lock(self, tmp_path):
        """tune() holds the module tune lock through its measured
        section, so two concurrent tunes can never interleave their
        candidate overlays (the docstring promise)."""
        x, work = _resplit_workload()
        seen = []

        def spying_work():
            seen.append(at._TUNE_LOCK.locked())
            return work()

        res = at.tune(
            "resplit", spying_work, signature=("rs", x.shape, 0, 1),
            search=SEARCH_PLAN, trials_per_config=2,
            db_dir=str(tmp_path / "db"),
        )
        assert not res.from_db
        assert seen and all(seen)

    def test_error_budget_refuses_lossy_modes(self, tmp_path):
        """With a budget tighter than any quantized mode's error, every
        lossy candidate is rejected and the winner stays exact."""
        reg = tm.enable()
        reg.clear()
        try:
            x, work = _resplit_workload()
            res = at.tune(
                "resplit", work, signature=("rb", x.shape, 0, 1),
                search=SEARCH_PREC, error_budget=1e-12,
                trials_per_config=2, db_dir=str(tmp_path / "db"),
            )
            assert res.config["HEAT_TPU_COLLECTIVE_PREC"] == "off"
            assert reg.counters["autotune.rejected_budget"] >= 1
            assert res.record["validation"] == "digest"
        finally:
            tm.disable()

    def test_budgeted_lossy_pick_is_within_budget(self, tmp_path):
        budget = 1.05 / 127  # the int8 single-hop bound the CI gate pins
        x, work = _resplit_workload()
        res = at.tune(
            "resplit", work, signature=("rl", x.shape, 0, 1),
            search=SEARCH_PREC, error_budget=budget,
            trials_per_config=2, db_dir=str(tmp_path / "db"),
        )
        rec = res.record
        assert rec["tuned_wall"] <= rec["baseline_wall"]
        assert rec["max_rel_err"] <= budget
        assert rec["error_budget"] == budget

    def test_exact_site_pin_beats_tuned_overlay(self):
        """An adopted lossy overlay must not leak into exact-semantics
        sites: the per-call precision='off' pin wins (HL003 contract),
        so sort stays bit-identical under a tuned int8 overlay."""
        rng = np.random.default_rng(3)
        xn = rng.standard_normal((64, 8)).astype(np.float32)
        x = ht.array(xn, split=0)

        def sorted_digest():
            vals, idx = ht.sort(x, axis=0)
            return trials.digest((vals.numpy(), idx.numpy()))

        ref = sorted_digest()
        at._adopt("resplit", {"HEAT_TPU_COLLECTIVE_PREC": "int8"})
        assert collective_prec.mode() == "int8"  # overlay is live...
        assert collective_prec.resolve("off") == "off"  # ...pin wins
        assert sorted_digest() == ref

    def test_broken_candidate_is_disqualified_not_fatal(self, tmp_path):
        reg = tm.enable()
        reg.clear()
        try:
            calls = {"n": 0}

            def work():
                calls["n"] += 1
                if knobs.get("HEAT_TPU_RELAYOUT_PLAN") == "chunked":
                    raise RuntimeError("boom")
                return np.ones(3)

            res = at.tune(
                "flaky", work, signature="f", search=SEARCH_PLAN,
                trials_per_config=2, db_dir=str(tmp_path / "db"),
            )
            assert res.config["HEAT_TPU_RELAYOUT_PLAN"] != "chunked"
            assert reg.counters["autotune.rejected_error"] == 1
        finally:
            tm.disable()


# -- telemetry: counters / events / summarize / trace -------------------------


class TestTelemetry:
    def test_live_and_offline_summaries_agree(self, tmp_path):
        """report.summarize()'s offline event replay must reconstruct
        the SAME autotune block as the live counters (the PR-5
        resilience reconciliation, applied to the new subsystem)."""
        reg = tm.enable()
        reg.clear()
        try:
            x, work = _resplit_workload()
            kwargs = dict(
                signature=("rt", x.shape, 0, 1), search=SEARCH_PLAN,
                trials_per_config=2, db_dir=str(tmp_path / "db"),
            )
            at.tune("resplit", work, **kwargs)
            at.reset()
            at.tune("resplit", work, **kwargs)  # db hit path too
            live = tm.report.summarize()["autotune"]
            offline = tm.report.summarize(list(reg.events))["autotune"]
            assert live == offline
            for key in ("trials", "picks", "stores", "db_misses",
                        "db_hits", "adopted"):
                assert live.get(key, 0) >= 1, (key, live)
        finally:
            tm.disable()

    def test_trace_gets_an_autotune_track(self):
        reg = tm.enable()
        reg.clear()
        try:
            at._emit("resplit", "pick", config={"k": "v"})
            rows = tm.trace.to_trace_events(reg.events)
            marks = [r for r in rows if r.get("cat") == "autotune"]
            assert marks and marks[0]["ph"] == "i"
            tid = marks[0]["tid"]
            names = [r for r in rows if r.get("name") == "thread_name"
                     and r["tid"] == tid]
            assert names and names[0]["args"]["name"] == "autotune"
        finally:
            tm.disable()

    def test_untuned_summary_shape_unchanged(self):
        reg = tm.enable()
        reg.clear()
        try:
            assert "autotune" not in tm.report.summarize()
        finally:
            tm.disable()


# -- dispatch integration -----------------------------------------------------


class TestDispatchIntegration:
    def test_default_off_is_the_pr10_dispatch_path(self, monkeypatch):
        """HEAT_TPU_AUTOTUNE=0: one flag check on the miss path, no DB
        reads, no autotune counters, no new compiles (CompileWatcher +
        counter oracle)."""
        monkeypatch.delenv("HEAT_TPU_AUTOTUNE", raising=False)

        def boom(*a, **k):  # any DB open under the off flag is a bug
            raise AssertionError("tuning DB consulted while disarmed")

        monkeypatch.setattr(at.db, "open_db", boom)
        reg = tm.enable()
        reg.clear()
        try:
            pc.reset()
            x, work = _resplit_workload(seed=7)
            work()  # miss path: flag check only
            with tm.CompileWatcher() as cw:
                work()  # warm path: dict lookup, zero compiles
            assert cw.backend_compiles == 0
            assert not any(
                c.startswith("autotune.") for c in reg.counters
            )
            assert not any(
                e.get("kind") == "autotune" for e in reg.events
            )
        finally:
            tm.disable()

    def test_warm_start_gates_lossy_records_on_ambient_budget(self, tmp_path):
        """Dispatch-time warm start applies the same budget gate as a
        tune()-time DB hit: a persisted LOSSY winner is only auto-adopted
        when the ambient HEAT_TPU_AUTOTUNE_BUDGET covers its measured
        error — a process that stated no budget never inherits quantized
        collectives from a shared DB."""
        budget = 1.05 / 127
        d = db.TuneDB(str(tmp_path / "db"))
        key = db.tune_key("resplit", "sig")
        d.store({
            "schema": db.SCHEMA, "key": key, "site": "resplit",
            "signature": "sig", "mesh": db.mesh_fingerprint(),
            "config": {"HEAT_TPU_COLLECTIVE_PREC": "int8"},
            "baseline_wall": 1.0, "tuned_wall": 0.5,
            "error_budget": budget, "max_rel_err": 0.004,
            "validation": "allclose", "created": 0.0,
        })
        at.enable(d.path)
        # no ambient budget: the lossy record is skipped, not adopted
        assert at.warm_start(force=True) == 0
        assert "resplit" not in at.adopted()
        assert knobs.raw("HEAT_TPU_COLLECTIVE_PREC") is None
        # a covering ambient budget admits it
        knobs.set_override("HEAT_TPU_AUTOTUNE_BUDGET", str(budget))
        assert at.warm_start(force=True) == 1
        assert at.adopted()["resplit"] == {"HEAT_TPU_COLLECTIVE_PREC": "int8"}
        # a tighter ambient budget refuses it again
        at.reset()
        knobs.set_override("HEAT_TPU_AUTOTUNE_BUDGET", "1e-12")
        assert at.warm_start(force=True) == 0
        assert "resplit" not in at.adopted()

    def test_readonly_consults_never_create_the_db_dir(self, tmp_path):
        """open_db/lookup/records/count (the bench probe, a disabled
        tuner with HEAT_TPU_TUNE_DB merely exported) must not create the
        DB directory as a side effect — only store() does."""
        path = str(tmp_path / "nonexistent_db")
        d = db.open_db(path)
        assert d is not None
        assert d.lookup(db.tune_key("resplit", "sig")) is None
        assert list(d.records()) == [] and d.count() == 0
        assert not os.path.exists(path)
        d.store({
            "schema": db.SCHEMA, "key": db.tune_key("resplit", "sig"),
            "site": "resplit", "signature": "sig",
            "mesh": db.mesh_fingerprint(),
            "config": {"HEAT_TPU_RELAYOUT_PLAN": "alltoall"},
            "created": 0.0,
        })
        assert os.path.isdir(path) and d.count() == 1

    def test_numpy_budget_and_store_failure_keep_the_winner(self, tmp_path):
        """A numpy-scalar budget is coerced before it can skew the
        comparisons or crash json.dump, and a store failure after a
        successful tune loses only persistence — the measured winner is
        still adopted and returned (it is adopted BEFORE the store)."""
        blocker = tmp_path / "blocker"
        blocker.write_text("not a directory")
        x, work = _resplit_workload()
        res = at.tune(
            "resplit", work, signature=("rn", x.shape, 0, 1),
            search=SEARCH_PREC, trials_per_config=2,
            error_budget=np.float32(1.05 / 127),  # numpy scalar budget
            db_dir=str(blocker / "db"),  # store() will fail: not a dir
        )
        assert not res.from_db and res.trials_run > 0
        assert isinstance(res.record["error_budget"], float)
        assert at.adopted()["resplit"] == res.config

    def test_program_miss_warm_starts_from_db(self, tmp_path, monkeypatch):
        """With the flag on, the FIRST program-cache miss adopts every
        persisted winner for this mesh — dispatch-time consult."""
        d = db.TuneDB(str(tmp_path / "db"))
        key = db.tune_key("resplit", "sig")
        d.store({
            "schema": db.SCHEMA, "key": key, "site": "resplit",
            "signature": "sig", "mesh": db.mesh_fingerprint(),
            "config": {"HEAT_TPU_RELAYOUT_PLAN": "alltoall"},
            "baseline_wall": 1.0, "tuned_wall": 0.5, "created": 0.0,
        })
        at.enable(d.path)
        pc.reset()
        pc.cached_program("t_at", "k", lambda: (lambda v: v))
        assert at.adopted()["resplit"] == {
            "HEAT_TPU_RELAYOUT_PLAN": "alltoall"
        }
        assert knobs.get("HEAT_TPU_RELAYOUT_PLAN") == "alltoall"

    def test_server_constructs_tuned(self, tmp_path):
        """A persisted serve config lands in the ladder of a freshly
        constructed Server (serve dispatch-time consult)."""
        d = db.TuneDB(str(tmp_path / "db"))
        key = db.tune_key("serve", "sig")
        d.store({
            "schema": db.SCHEMA, "key": key, "site": "serve",
            "signature": "sig", "mesh": db.mesh_fingerprint(),
            "config": {"HEAT_TPU_SERVE_MAX_BATCH": "16",
                       "HEAT_TPU_SERVE_MAX_WAIT_MS": "0.5"},
            "baseline_wall": 1.0, "tuned_wall": 0.5, "created": 0.0,
        })
        at.enable(d.path)
        server = ht.serve.Server()
        try:
            assert server.max_batch == 16
            assert server.ladder[-1] == 16
            assert server.max_wait == pytest.approx(0.5e-3)
        finally:
            server.close()


# -- second process (subprocess-verified acceptance path) ---------------------


@pytest.mark.slow
class TestSecondProcess:
    def test_second_process_zero_trials_zero_steady_compiles(self, tmp_path):
        """A fresh process pointed at a populated HEAT_TPU_TUNE_DB
        reaches the tuned config with zero measured trials, and its
        steady-state dispatch under the adopted config compiles
        nothing."""
        tune_db = str(tmp_path / "db")
        x, work = _resplit_workload(n=128, f=16, seed=1)
        first = at.tune(
            "resplit", work, signature=("sp", (128, 16), 0, 1),
            search=SEARCH_PLAN, trials_per_config=2, db_dir=tune_db,
        )
        assert not first.from_db
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count="
            + str(ht.get_comm().size),
            HEAT_TPU_AUTOTUNE="1",
            HEAT_TPU_TUNE_DB=tune_db,
            PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        )
        script = (
            "import numpy as np\n"
            "import heat_tpu as ht\n"
            "from heat_tpu import autotune as at\n"
            "x = ht.array(np.random.default_rng(1).standard_normal(\n"
            "    (128, 16)).astype(np.float32), split=0)\n"
            "work = lambda: x.resplit(1).larray\n"
            "res = at.tune('resplit', work,\n"
            "              signature=('sp', (128, 16), 0, 1),\n"
            "              search=['HEAT_TPU_RELAYOUT_PLAN'],\n"
            "              trials_per_config=2)\n"
            "assert res.from_db and res.trials_run == 0, (\n"
            "    res.from_db, res.trials_run)\n"
            "work()  # first dispatch under the adopted config compiles\n"
            "with ht.telemetry.CompileWatcher() as cw:\n"
            "    work()  # steady state: cached program, zero compiles\n"
            "assert cw.backend_compiles == 0, cw.backend_compiles\n"
            "print('TUNED', res.config)\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", script], env=env, cwd=REPO,
            capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "TUNED" in r.stdout
        assert str(first.config) in r.stdout
