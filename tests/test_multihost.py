"""Multi-host stage 1 (SURVEY §7): a REAL 2-process `jax.distributed` CPU
smoke test exercising `init_distributed` + multi-host `is_split` assembly
(VERDICT r2 item 6; reference factories.py:386-429 neighbor handshake,
communication.py:1867 MPI_WORLD construction under mpirun)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
rank, port = int(sys.argv[1]), sys.argv[2]
NPROCS, LDC = int(sys.argv[4]), int(sys.argv[5])  # topology: procs x local devices
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={LDC}"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import heat_tpu as ht

comm = ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=NPROCS, process_id=rank
)
assert jax.process_count() == NPROCS
assert comm.size == NPROCS * LDC, comm.size
assert comm.rank == rank

# --- is_split assembly: each process passes its canonical block ----------
n = 10  # non-divisible over every swept mesh; 4x2 leaves proc 3 EMPTY
c = comm.chunk_size(n)
lo = min(rank * LDC * c, n)
hi = min((rank + 1) * LDC * c, n)
local = np.arange(lo, hi, dtype=np.float32)
x = ht.array(local, is_split=0)
assert x.shape == (n,), x.shape
assert x.split == 0

# --- lshape reports the first LOCAL device's chunk, not process index ----
assert comm.first_local_position() == rank * LDC, comm.first_local_position()
_, exp_lshape, _ = comm.chunk((n,), 0, comm.first_local_position())
assert x.lshape == exp_lshape, (x.lshape, exp_lshape)

# --- global reductions over the assembled array (pad-neutralized) --------
total = float(ht.sum(x).item())
assert total == float(sum(range(n))), total
mx = float(ht.max(x).item())
assert mx == n - 1.0, mx

# --- RAGGED blocks assemble via the staging gather (reference parity:
# arbitrary per-rank extents, factories.py:386-429) ----------------------
rag_lens = [3 + r for r in range(NPROCS)]
rag_prefix = sum(rag_lens[:rank])
ragged = np.arange(
    rag_prefix, rag_prefix + rag_lens[rank], dtype=np.float32
)
xr = ht.array(ragged, is_split=0)
n_rag = sum(rag_lens)
assert xr.shape == (n_rag,), xr.shape
assert abs(float(ht.sum(xr).item()) - float(sum(range(n_rag)))) < 1e-3
assert float(ht.max(xr).item()) == n_rag - 1.0
# order preserved: sorted equals itself
srt, _ = ht.sort(xr)
assert float(ht.max(ht.abs(srt - xr)).item()) == 0.0

# ======= stage 2: real compute across the two hosts =======================
# Verification discipline: results of cross-host ops are checked through
# replicated scalars (psum'd reductions / .item()) — gathering a
# non-fully-addressable array to one host is exactly what multi-host
# forbids, and the guards enforce that.

# elementwise chain on the split array (physical path, no relayout)
y = (x * 2.0 + 1.0) / 2.0
assert abs(float(ht.sum(y).item()) - (sum(range(n)) + 0.5 * n)) < 1e-4

# 2-D assembly + axis reduction: rows [0,6) on proc0, [6,10) on proc1
m2 = np.stack([local, 10.0 * local], axis=1)  # (local_rows, 2)
X2 = ht.array(m2, is_split=0)
assert X2.shape == (n, 2) and X2.split == 0
col = ht.sum(X2, axis=0)  # replicated (2,)
s0, s1 = float(col[0].item()), float(col[1].item())
assert abs(s0 - sum(range(n))) < 1e-3 and abs(s1 - 10.0 * sum(range(n))) < 1e-2

# distributed matmul: (n,2) split=0 @ (2,2) replicated -> (n,2) split=0
W = ht.array(np.asarray([[1.0, 1.0], [0.0, 1.0]], dtype=np.float32))
P = ht.matmul(X2, W)
assert P.split == 0 and P.shape == (n, 2)
# column sums of the product: [sum(x), sum(x) + 10 sum(x)]
pc = ht.sum(P, axis=0)
assert abs(float(pc[0].item()) - sum(range(n))) < 1e-3
assert abs(float(pc[1].item()) - 11.0 * sum(range(n))) < 1e-2

# mean/var over the split axis (pad-neutralized cross-host reductions)
mu = float(ht.mean(x).item())
assert abs(mu - (n - 1) / 2.0) < 1e-5, mu
va = float(ht.var(x).item())
assert abs(va - float(np.var(np.arange(n)))) < 1e-4, va

# distributed sort across the hosts: descending input, shard_map network
rev = ht.array(local[::-1].copy(), is_split=0)  # locally reversed blocks
sorted_x, _ = ht.sort(rev)
# correctness via an on-device comparison against the assembled ascending
# array (both split=0): max |sorted - x| == 0
diff = float(ht.max(ht.abs(sorted_x - x)).item())
assert diff == 0.0, diff

# ======= stage 3: distributed statistics / compaction ops cross-host ======
# (all of these avoid _logical by design, so they must work multi-host)

# percentile/median: distributed sort + order-statistic gather
p50 = float(ht.percentile(x, 50.0).item())
assert abs(p50 - (n - 1) / 2.0) < 1e-9, p50
assert abs(float(ht.median(x).item()) - (n - 1) / 2.0) < 1e-9

# histogram/bincount: per-shard counts + one psum (replicated results)
h, e = ht.histogram(x, bins=5, range=(0.0, float(n)))
assert int(np.asarray(h.larray).sum()) == n
cnt = ht.bincount(ht.array((local % 3).astype(np.int64), is_split=0))
assert int(np.asarray(cnt.larray).sum()) == n

# nonzero + masked select: scatter compaction, split=0 results
nz = ht.nonzero(x)  # the assembled array has one zero (position 0)
assert nz.shape == (n - 1, 1) and nz.split == 0, nz.shape
sel = x[x > 4.5]
assert sel.shape == (n - 5,) and sel.split == 0
assert abs(float(ht.sum(sel).item()) - float(sum(range(5, n)))) < 1e-4

# topk: two-stage select over both hosts
tv, ti = ht.topk(x, 3)
assert [float(v) for v in np.asarray(tv.larray)] == [9.0, 8.0, 7.0]

# diff: halo stencil across the host boundary (telescoping sum = x[-1]-x[0])
d = ht.diff(x)
assert d.split == 0 and d.shape == (n - 1,)
assert abs(float(ht.sum(d).item()) - (n - 1.0)) < 1e-6

# ======= stage 3b: multi-host load_csv — per-process row-range tokenize ===
import time
csv_path = sys.argv[3]
if rank == 0:
    tmp_csv = csv_path + ".tmp"
    with open(tmp_csv, "w") as f:
        f.write("c0,c1\n")
        for i in range(11):  # 11 rows over 4 devices: uneven, pads in play
            f.write(f"{i},{10 * i}\n")
    os.replace(tmp_csv, csv_path)  # atomic publish
else:
    for _ in range(200):
        if os.path.exists(csv_path):
            break
        time.sleep(0.05)
X = ht.load_csv(csv_path, header_lines=1, split=0)
assert X.shape == (11, 2) and X.split == 0, X.shape
cols = ht.sum(X, axis=0)
assert abs(float(cols[0].item()) - 55.0) < 1e-3
assert abs(float(cols[1].item()) - 550.0) < 1e-2
# wrong split axis raises the documented guard
try:
    ht.load_csv(csv_path, header_lines=1, split=1)
except NotImplementedError:
    pass
else:
    raise AssertionError("multi-host load_csv split=1 must raise")

# multi-host save_csv: serialized per-process slab writes, no gather
csv_out = csv_path + ".out.csv"
ht.save_csv(X, csv_out)
got = np.loadtxt(csv_out, delimiter=",")
ref = np.stack([np.arange(11.0), 10.0 * np.arange(11.0)], axis=1)
assert got.shape == (11, 2) and np.allclose(got, ref), got

# ======= stage 4: sharded HDF5 I/O — per-process slab reads/writes ========
if ht.supports_hdf5():
    import h5py

    R, C = 11, 3  # 11 rows over 4 devices: uneven split=0; 3 cols: uneven split=1
    ref_h5 = np.arange(R * C, dtype=np.float32).reshape(R, C)
    h5_path = csv_path + ".h5"
    if rank == 0:
        tmp_h5 = h5_path + ".tmp"
        with h5py.File(tmp_h5, "w") as f:
            f.create_dataset("data", data=ref_h5)
        os.replace(tmp_h5, h5_path)
    else:
        for _ in range(200):
            if os.path.exists(h5_path):
                break
            time.sleep(0.05)

    # load split=0: this process range-reads ONLY its row slab
    A = ht.load_hdf5(h5_path, "data", split=0)
    assert A.shape == (R, C) and A.split == 0, (A.shape, A.split)
    ac = ht.sum(A, axis=0)
    for j in range(C):
        assert abs(float(ac[j].item()) - float(ref_h5[:, j].sum())) < 1e-2

    # load split=1: uneven column chunks (ceil(3/4)=1; proc1's tail is short)
    B = ht.load_hdf5(h5_path, "data", split=1)
    assert B.shape == (R, C) and B.split == 1, (B.shape, B.split)
    br = ht.sum(B, axis=1)
    assert abs(float(ht.sum(br).item()) - float(ref_h5.sum())) < 1e-2

    # save from the split array: slab writes in process order, then verify
    out_h5 = h5_path + ".out.h5"
    ht.save_hdf5(A, out_h5, "data")
    with h5py.File(out_h5, "r") as f:
        got = np.asarray(f["data"])
    assert got.shape == (R, C) and np.array_equal(got, ref_h5)

    # save a split=1 array too (slab writes along columns)
    out_h5b = h5_path + ".out1.h5"
    ht.save_hdf5(B, out_h5b, "data")
    with h5py.File(out_h5b, "r") as f:
        got1 = np.asarray(f["data"])
    assert np.array_equal(got1, ref_h5)

    # a writer failure must raise on EVERY process, not strand the barrier
    # ring: re-creating an existing dataset under mode="r+" collides
    try:
        ht.save_hdf5(A, out_h5, "data", mode="r+")
    except Exception:
        pass
    else:
        raise AssertionError("duplicate dataset create must raise")

    # replicated multi-host save: exactly one writer
    rep = ht.array(ref_h5[:4])
    out_h5c = h5_path + ".rep.h5"
    ht.save_hdf5(rep, out_h5c, "data")
    with h5py.File(out_h5c, "r") as f:
        assert np.array_equal(np.asarray(f["data"]), ref_h5[:4])

    # column-split save_csv raises the documented guard
    try:
        ht.save_csv(B, csv_out + ".bad")
    except NotImplementedError:
        pass
    else:
        raise AssertionError("multi-host save_csv split=1 must raise")

# ======= stage 4b: sharded NetCDF I/O — slab reads + serialized writes ====
if ht.supports_netcdf():
    R, C = 11, 3
    ref_nc = np.arange(R * C, dtype=np.float32).reshape(R, C)
    nc_out = csv_path + ".out.nc"
    # save from a split=0 array: process-ordered slab writes, no gather
    Anc = ht.array(ref_nc, split=0)
    ht.save_netcdf(Anc, nc_out, "data")
    # load split=0/1: per-process slab range reads + is_split assembly
    L0 = ht.load_netcdf(nc_out, "data", split=0)
    assert L0.shape == (R, C) and L0.split == 0, (L0.shape, L0.split)
    assert abs(float(ht.sum(L0).item()) - float(ref_nc.sum())) < 1e-2
    L1 = ht.load_netcdf(nc_out, "data", split=1)
    assert L1.shape == (R, C) and L1.split == 1
    assert abs(float(ht.sum(L1).item()) - float(ref_nc.sum())) < 1e-2
    # replicated multi-host save: exactly one writer
    nc_rep = csv_path + ".rep.nc"
    ht.save_netcdf(ht.array(ref_nc[:4]), nc_rep, "data")
    Lr = ht.load_netcdf(nc_rep, "data")
    assert abs(float(ht.sum(Lr).item()) - float(ref_nc[:4].sum())) < 1e-2

# ======= stage 5: npy slab I/O — memmap reads, slab writes ================
npy_path = csv_path + ".npy"
ref_npy = np.arange(11 * 3, dtype=np.float32).reshape(11, 3)
if rank == 0:
    tmp_npy = npy_path + ".tmp.npy"
    np.save(tmp_npy, ref_npy)
    os.replace(tmp_npy, npy_path)
else:
    for _ in range(200):
        if os.path.exists(npy_path):
            break
        time.sleep(0.05)
An = ht.load_npy(npy_path, split=0)
assert An.shape == (11, 3) and An.split == 0
assert abs(float(ht.sum(An).item()) - float(ref_npy.sum())) < 1e-2
out_npy = npy_path + ".out.npy"
ht.save_npy(An, out_npy)
got_npy = np.load(out_npy)
assert np.array_equal(got_npy, ref_npy)
# split=1 load (uneven column chunks)
Acn = ht.load_npy(npy_path, split=1)
assert Acn.split == 1 and Acn.shape == (11, 3)

# ======= stage 6: DataLoader — per-process slab batching ==================
ND = 32
dl_c = comm.chunk_size(ND)
dl_lo = min(rank * LDC * dl_c, ND)
dl_hi = min((rank + 1) * LDC * dl_c, ND)
dl_local = np.stack(
    [np.arange(dl_lo, dl_hi, dtype=np.float32)] * 2, axis=1
)  # (rows, 2)
Xd = ht.array(dl_local, is_split=0)
Yd = ht.array(np.arange(dl_lo, dl_hi, dtype=np.float32), is_split=0)
import jax.numpy as jnp
from heat_tpu.utils.data import DataLoader, Dataset

ds = Dataset(Xd, targets=Yd)
loader = DataLoader(ds, batch_size=8, shuffle=False)
nb = len(loader)
assert nb >= 2, nb
tot = 0.0
rows = 0
for xb, yb in loader:
    assert xb.shape[0] == 8 and xb.shape[1] == 2, xb.shape
    tot += float(jnp.sum(xb[:, 0]))
    rows += xb.shape[0]
assert rows == nb * 8
assert abs(tot - float(sum(range(ND)))) < 1e-3, tot

# shuffled epochs preserve the total
import jax.numpy as jnp2
loader2 = DataLoader(Dataset(Xd, targets=Yd), batch_size=8, shuffle=True)
for _ in range(2):
    tot2 = 0.0
    for xb, yb in loader2:
        tot2 += float(jnp2.sum(xb[:, 0]))
    assert abs(tot2 - float(sum(range(ND)))) < 1e-3, tot2

print(f"RANK{rank}_OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


OP_WORKER = r"""
import os, sys, traceback
rank, port = int(sys.argv[1]), sys.argv[2]
NPROCS, LDC = int(sys.argv[3]), int(sys.argv[4])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={LDC}"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import heat_tpu as ht
from tests.mh_op_table import OPS, N

comm = ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=NPROCS, process_id=rank
)

def block(glob, axis):
    c = comm.chunk_size(glob.shape[axis])
    lo = min(rank * LDC * c, glob.shape[axis])
    hi = min((rank + 1) * LDC * c, glob.shape[axis])
    sl = [slice(None)] * glob.ndim
    sl[axis] = slice(lo, hi)
    return glob[tuple(sl)]

xg = np.arange(N, dtype=np.float32)
Xg = np.arange(3 * N, dtype=np.float32).reshape(N, 3)
Xcg = np.arange(60, dtype=np.float32).reshape(6, 10)
ig = (np.arange(N) % 3).astype(np.int64)
ctx = {
    "x": ht.array(block(xg, 0), is_split=0),
    "X": ht.array(block(Xg, 0), is_split=0),
    "Xc": ht.array(block(Xcg, 1), is_split=1),
    "ints": ht.array(block(ig, 0), is_split=0),
}

failures = []
for name, fn, expect in OPS:
    try:
        fn(ht, np, ctx)
        outcome = "ok"
        err = None
    except Exception as e:  # noqa: BLE001 — the sweep records everything
        outcome = "raises"
        err = traceback.format_exc()
    if outcome != expect:
        failures.append((name, expect, outcome, (err or "")[-500:]))
for name, expect, outcome, err in failures:
    print(f"OP FAIL {name}: expected {expect}, got {outcome}\n{err}", flush=True)
if not failures:
    print(f"RANK{rank}_OPS_OK ({len(OPS)} ops)", flush=True)
"""


def _record_ci_r6(name: str, outs) -> None:
    """Persist a topology run's per-rank output under artifacts/ci_r6/
    (VERDICT r5 #8: the multi-host breadth sweep leaves a committed
    record). Best-effort — an unwritable checkout must not fail the test."""
    try:
        d = os.path.join(REPO, "artifacts", "ci_r6")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, f"{name}.log"), "w") as f:
            for r, out in enumerate(outs):
                f.write(f"===== rank {r} =====\n{out}\n")
    except OSError:
        pass


class TestMultiHostStage1:
    """The worker list runs under three topologies — 2 procs × 4 devices,
    4 procs × 2 devices (the same 8-position mesh; SURVEY §4's world-size
    sweep, VERDICT r3 item 9), and 4 procs × 1 device (VERDICT r5 #8:
    4-way process breadth on a 4-position mesh, one device per process —
    the pure-DCN shape). The 10-row gshape is non-divisible under all
    three, and 4×2 leaves the last process with an EMPTY canonical
    block. Results are recorded under artifacts/ci_r6/."""

    @pytest.mark.parametrize("nprocs,ldc", [(2, 4), (4, 2), (4, 1)])
    @pytest.mark.slow
    def test_process_topologies(self, tmp_path, nprocs, ldc):
        script = tmp_path / "mh_worker.py"
        script.write_text(WORKER)
        port = _free_port()
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        # the workers force their own XLA_FLAGS before importing jax
        procs = [
            subprocess.Popen(
                [
                    sys.executable, str(script), str(r), str(port),
                    str(tmp_path / "mh_data.csv"), str(nprocs), str(ldc),
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                env=env,
                cwd=REPO,
            )
            for r in range(nprocs)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=360)
                outs.append(out.decode(errors="replace"))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        _record_ci_r6(f"multihost_{nprocs}x{ldc}", outs)
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {r} failed:\n{out}"
            assert f"RANK{r}_OK" in out, f"rank {r} output:\n{out}"


class TestMultiHostOpSurface:
    """Run the op-surface table (tests/mh_op_table.py) inside a real
    2-process run and assert run-or-documented-raise for every row
    (VERDICT r3 item 4)."""

    @pytest.mark.parametrize("nprocs,ldc", [(2, 2)])
    @pytest.mark.slow
    def test_op_table(self, tmp_path, nprocs, ldc):
        script = tmp_path / "mh_ops.py"
        script.write_text(OP_WORKER)
        port = _free_port()
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(r), str(port), str(nprocs), str(ldc)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                env=env,
                cwd=REPO,
            )
            for r in range(nprocs)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=600)
                outs.append(out.decode(errors="replace"))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {r} crashed:\n{out}"
            assert f"RANK{r}_OPS_OK" in out, f"rank {r} op failures:\n{out}"


class TestOpTableSingleController:
    """The same table's "ok" rows must hold on the single-controller
    8-device mesh (guards the table itself against rot)."""

    @pytest.mark.slow
    def test_ok_rows(self):
        import numpy as np

        import heat_tpu as ht
        from .mh_op_table import N, OPS

        ctx = {
            "x": ht.array(np.arange(N, dtype=np.float32), split=0),
            "X": ht.array(np.arange(3 * N, dtype=np.float32).reshape(N, 3), split=0),
            "Xc": ht.array(np.arange(60, dtype=np.float32).reshape(6, 10), split=1),
            "ints": ht.array((np.arange(N) % 3).astype(np.int64), split=0),
        }
        for name, fn, expect in OPS:
            if expect != "ok":
                continue
            fn(ht, np, ctx)  # must not raise


class TestLogicalGuard:
    def test_logical_single_process_ok(self):
        import heat_tpu as ht

        x = ht.arange(11, dtype=ht.float32, split=0)
        assert x._logical().shape == (11,)
