"""Deep ML-algorithm checks — estimator-contract sweeps (get/set params,
refit idempotence, split invariance of predictions), spatial-kernel
equivalences, and oracle comparisons against closed-form results
(reference heat/cluster|regression|naive_bayes/tests drive the same
sklearn-style contracts per rank)."""

import numpy as np
import pytest

import heat_tpu as ht
from .basic_test import TestCase


def blobs(p, n_per=12, d=4, k=3, seed=0, spread=8.0):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((k, d)).astype(np.float32) * spread
    pts = np.concatenate(
        [centers[i] + rng.standard_normal((n_per, d)).astype(np.float32) for i in range(k)]
    )
    labels = np.repeat(np.arange(k), n_per)
    perm = rng.permutation(len(pts))
    return pts[perm], labels[perm], centers


class TestEstimatorContract(TestCase):
    """BaseEstimator get_params/set_params round-trips (reference
    core/base.py contract) for every estimator family."""

    def _roundtrip(self, est):
        params = est.get_params()
        assert isinstance(params, dict) and params
        est.set_params(**params)
        assert est.get_params() == params

    def test_kmeans_params(self):
        self._roundtrip(ht.cluster.KMeans(n_clusters=4, max_iter=7))

    def test_kmedians_params(self):
        self._roundtrip(ht.cluster.KMedians(n_clusters=2))

    def test_kmedoids_params(self):
        self._roundtrip(ht.cluster.KMedoids(n_clusters=2))

    def test_lasso_params(self):
        self._roundtrip(ht.regression.Lasso(lam=0.05, max_iter=20))

    def test_gnb_params(self):
        self._roundtrip(ht.naive_bayes.GaussianNB())

    def test_knn_params(self):
        self._roundtrip(ht.classification.KNeighborsClassifier(n_neighbors=3))

    def test_set_params_unknown_key_raises(self):
        est = ht.cluster.KMeans()
        with pytest.raises((ValueError, TypeError)):
            est.set_params(definitely_not_a_param=1)


class TestSplitInvariance(TestCase):
    """Fitting on split vs replicated data must give the same model —
    the core promise of the framework (SURVEY §2.4: 'pure ht-ops →
    automatically distributed')."""

    def test_kmeans_split_invariant(self):
        pts, _, _ = blobs(self.comm.size, seed=1)
        m_rep = ht.cluster.KMeans(n_clusters=3, init="random", random_state=5, max_iter=30)
        m_rep.fit(ht.array(pts, split=None))
        m_split = ht.cluster.KMeans(n_clusters=3, init="random", random_state=5, max_iter=30)
        m_split.fit(ht.array(pts, split=0))
        np.testing.assert_allclose(
            np.sort(m_rep.cluster_centers_.numpy(), axis=0),
            np.sort(m_split.cluster_centers_.numpy(), axis=0),
            rtol=1e-4, atol=1e-4,
        )

    def test_gnb_split_invariant(self):
        pts, labels, _ = blobs(self.comm.size, seed=2)
        preds = []
        for split in (None, 0):
            m = ht.naive_bayes.GaussianNB()
            m.fit(ht.array(pts, split=split), ht.array(labels, split=split))
            preds.append(m.predict(ht.array(pts, split=split)).numpy())
        np.testing.assert_array_equal(preds[0], preds[1])

    def test_lasso_split_invariant(self):
        rng = np.random.default_rng(3)
        n, d = 8 * self.comm.size, 6
        X = rng.standard_normal((n, d)).astype(np.float32)
        w = np.zeros(d, dtype=np.float32)
        w[:2] = [2.0, -3.0]
        y = X @ w
        coefs = []
        for split in (None, 0):
            m = ht.regression.Lasso(lam=0.01, max_iter=200)
            m.fit(ht.array(X, split=split), ht.array(y[:, None], split=split))
            coefs.append(np.asarray(m.theta.numpy()).ravel())
        np.testing.assert_allclose(coefs[0], coefs[1], rtol=1e-4, atol=1e-4)

    def test_knn_split_invariant(self):
        pts, labels, _ = blobs(self.comm.size, seed=4)
        preds = []
        for split in (None, 0):
            m = ht.classification.KNeighborsClassifier(n_neighbors=3)
            m.fit(ht.array(pts, split=split), ht.array(labels, split=split))
            preds.append(m.predict(ht.array(pts, split=split)).numpy())
        np.testing.assert_array_equal(preds[0], preds[1])


class TestKMeansDeep(TestCase):
    def test_plusplus_init_beats_degenerate(self):
        pts, _, centers = blobs(self.comm.size, n_per=20, k=3, seed=5)
        m = ht.cluster.KMeans(n_clusters=3, init="kmeans++", random_state=0, max_iter=50)
        m.fit(ht.array(pts, split=0))
        got = np.sort(m.cluster_centers_.numpy(), axis=0)
        want = np.sort(centers, axis=0)
        # every true center recovered within the blob radius
        assert np.abs(got - want).max() < 2.5

    def test_predict_assigns_nearest(self):
        pts, _, _ = blobs(self.comm.size, seed=6)
        m = ht.cluster.KMeans(n_clusters=3, random_state=1, max_iter=30)
        m.fit(ht.array(pts, split=0))
        labels = m.predict(ht.array(pts, split=0)).numpy().ravel()
        c = m.cluster_centers_.numpy()
        d = ((pts[:, None, :] - c[None]) ** 2).sum(-1)
        np.testing.assert_array_equal(labels, d.argmin(1))

    def test_functional_value_decreases_with_iters(self):
        pts, _, _ = blobs(self.comm.size, seed=7)
        x0 = ht.array(pts, split=0)

        def inertia(model):
            c = model.cluster_centers_.numpy()
            d = ((pts[:, None, :] - c[None]) ** 2).sum(-1)
            return d.min(1).sum()

        m1 = ht.cluster.KMeans(n_clusters=3, init="random", random_state=9, max_iter=1)
        m1.fit(x0)
        m20 = ht.cluster.KMeans(n_clusters=3, init="random", random_state=9, max_iter=20)
        m20.fit(x0)
        assert inertia(m20) <= inertia(m1) + 1e-3

    def test_n_clusters_one(self):
        pts, _, _ = blobs(self.comm.size, seed=8)
        m = ht.cluster.KMeans(n_clusters=1, max_iter=10)
        m.fit(ht.array(pts, split=0))
        np.testing.assert_allclose(
            m.cluster_centers_.numpy().ravel(), pts.mean(0), rtol=1e-3, atol=1e-3
        )


class TestSpatialDeep(TestCase):
    def test_cdist_self_distance_zero_diagonal(self):
        rng = np.random.default_rng(9)
        x = rng.standard_normal((2 * self.comm.size + 1, 5)).astype(np.float32)
        d = ht.spatial.cdist(ht.array(x, split=0)).numpy()
        np.testing.assert_allclose(np.diag(d), 0.0, atol=1e-3)
        np.testing.assert_allclose(d, d.T, atol=1e-3)

    def test_cdist_xy_asymmetric_shapes(self):
        rng = np.random.default_rng(10)
        x = rng.standard_normal((self.comm.size + 2, 4)).astype(np.float32)
        y = rng.standard_normal((7, 4)).astype(np.float32)
        want = np.sqrt(((x[:, None] - y[None]) ** 2).sum(-1))
        for sx in (None, 0):
            for sy in (None, 0):
                got = ht.spatial.cdist(ht.array(x, split=sx), ht.array(y, split=sy))
                np.testing.assert_allclose(got.numpy(), want, rtol=1e-3, atol=1e-3)

    def test_quadratic_vs_exact_agree(self):
        rng = np.random.default_rng(11)
        x = rng.standard_normal((10, 3)).astype(np.float32)
        exact = ht.spatial.cdist(ht.array(x, split=0)).numpy()
        quad = ht.spatial.cdist(ht.array(x, split=0), quadratic_expansion=True).numpy()
        np.testing.assert_allclose(exact, quad, rtol=1e-2, atol=1e-2)

    def test_manhattan_oracle(self):
        rng = np.random.default_rng(12)
        x = rng.standard_normal((self.comm.size + 1, 3)).astype(np.float32)
        want = np.abs(x[:, None] - x[None]).sum(-1)
        got = ht.spatial.manhattan(ht.array(x, split=0)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_rbf_kernel_properties(self):
        rng = np.random.default_rng(13)
        x = rng.standard_normal((8, 3)).astype(np.float32)
        k = ht.spatial.rbf(ht.array(x, split=0), sigma=2.0).numpy()
        np.testing.assert_allclose(np.diag(k), 1.0, atol=1e-4)
        assert (k > 0).all() and (k <= 1 + 1e-6).all()

    def test_ring_vs_gemm_path_identical(self):
        rng = np.random.default_rng(14)
        n = 4 * self.comm.size
        x = rng.standard_normal((n, 4)).astype(np.float32)
        a = ht.spatial.cdist(ht.array(x, split=0), ring=False).numpy()
        b = ht.spatial.cdist(ht.array(x, split=0), ring=True).numpy()
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-3)


class TestGaussianNBDeep(TestCase):
    def test_proba_rows_sum_to_one(self):
        pts, labels, _ = blobs(self.comm.size, seed=15)
        m = ht.naive_bayes.GaussianNB()
        m.fit(ht.array(pts, split=0), ht.array(labels, split=0))
        proba = m.predict_proba(ht.array(pts, split=0)).numpy()
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-4)

    def test_partial_fit_matches_full_fit(self):
        pts, labels, _ = blobs(self.comm.size, n_per=16, seed=16)
        full = ht.naive_bayes.GaussianNB()
        full.fit(ht.array(pts, split=0), ht.array(labels, split=0))
        inc = ht.naive_bayes.GaussianNB()
        half = len(pts) // 2
        classes = ht.array(np.unique(labels))
        inc.partial_fit(
            ht.array(pts[:half], split=0), ht.array(labels[:half], split=0), classes=classes
        )
        inc.partial_fit(ht.array(pts[half:], split=0), ht.array(labels[half:], split=0))
        np.testing.assert_allclose(
            full.theta_.numpy(), inc.theta_.numpy(), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            full.var_.numpy(), inc.var_.numpy(), rtol=1e-3, atol=1e-5
        )

    def test_priors_override(self):
        pts, labels, _ = blobs(self.comm.size, k=2, seed=17)
        labels = labels % 2
        m = ht.naive_bayes.GaussianNB(priors=ht.array(np.asarray([0.9, 0.1], dtype=np.float32)))
        m.fit(ht.array(pts, split=0), ht.array(labels, split=0))
        np.testing.assert_allclose(m.class_prior_.numpy(), [0.9, 0.1], rtol=1e-5)


class TestLassoDeep(TestCase):
    def test_soft_threshold_kills_small_coeffs(self):
        rng = np.random.default_rng(18)
        n, d = 10 * self.comm.size, 8
        X = rng.standard_normal((n, d)).astype(np.float32)
        w = np.zeros(d, dtype=np.float32)
        w[0] = 5.0
        y = X @ w
        m = ht.regression.Lasso(lam=0.5, max_iter=300)
        m.fit(ht.array(X, split=0), ht.array(y[:, None], split=0))
        coef = np.asarray(m.theta.numpy()).ravel()[1:]  # drop intercept row
        assert np.abs(coef[0]) > 1.0  # true signal survives
        assert np.abs(coef[1:]).max() < 0.3  # noise coordinates shrunk

    def test_lam_zero_reduces_to_least_squares(self):
        rng = np.random.default_rng(19)
        n, d = 12 * self.comm.size, 3
        X = rng.standard_normal((n, d)).astype(np.float32)
        w = np.asarray([1.0, -2.0, 0.5], dtype=np.float32)
        y = X @ w
        m = ht.regression.Lasso(lam=1e-6, max_iter=500, tol=1e-12)
        m.fit(ht.array(X, split=0), ht.array(y[:, None], split=0))
        coef = np.asarray(m.theta.numpy()).ravel()[1:]
        np.testing.assert_allclose(coef, w, rtol=1e-2, atol=1e-2)


class TestLaplacianDeep(TestCase):
    def test_row_sums_zero_unnormalized(self):
        rng = np.random.default_rng(20)
        x = rng.standard_normal((2 * self.comm.size, 3)).astype(np.float32)
        lap = ht.graph.Laplacian(
            lambda a: ht.spatial.rbf(a, sigma=1.0), definition="simple",
            mode="fully_connected",
        )
        L = lap.construct(ht.array(x, split=0)).numpy()
        np.testing.assert_allclose(L.sum(axis=1), 0.0, atol=1e-3)

    def test_symmetric_normalized_diagonal_ones(self):
        rng = np.random.default_rng(21)
        x = rng.standard_normal((2 * self.comm.size, 3)).astype(np.float32)
        lap = ht.graph.Laplacian(
            lambda a: ht.spatial.rbf(a, sigma=1.0), definition="norm_sym",
            mode="fully_connected",
        )
        L = lap.construct(ht.array(x, split=0)).numpy()
        np.testing.assert_allclose(np.diag(L), 1.0, atol=1e-3)
