"""Tests for the int8 quantized matmul Pallas kernel.

Off-TPU the kernel runs under the Pallas interpreter — the same program
that compiles to Mosaic on chip. Oracle: float matmul within symmetric-
quantization error bounds, and an exact integer oracle on the int32
accumulation path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heat_tpu.core.linalg import int8_matmul, matmul_int8, quantize_int8


class TestQuantize:
    def test_roundtrip_error_bounded(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
        q, s = quantize_int8(x, axis=1)
        assert q.dtype == jnp.int8 and s.shape == (64, 1)
        err = np.abs(np.asarray(q, np.float32) * np.asarray(s) - np.asarray(x))
        # symmetric absmax: per-row error <= scale/2
        assert (err <= np.asarray(s) / 2 + 1e-7).all()

    def test_zero_row_safe(self):
        x = jnp.zeros((4, 8), jnp.float32)
        q, s = quantize_int8(x, axis=1)
        assert np.asarray(q).sum() == 0 and np.isfinite(np.asarray(s)).all()


class TestInt8Matmul:
    def test_integer_exact(self):
        # integers well inside int8: quantization is exact, result must be too
        rng = np.random.default_rng(1)
        a = jnp.asarray(rng.integers(-7, 8, (40, 24)), jnp.float32)
        b = jnp.asarray(rng.integers(-7, 8, (24, 56)), jnp.float32)
        # scale=1 quantization: feed ints directly
        out = int8_matmul(a.astype(jnp.int8), jnp.ones((40, 1), jnp.float32),
                          b.astype(jnp.int8), jnp.ones((1, 56), jnp.float32),
                          block_m=32, block_n=128, block_k=128)
        ref = np.asarray(a, np.int64) @ np.asarray(b, np.int64)
        np.testing.assert_array_equal(np.asarray(out), ref.astype(np.float32))

    def test_matches_float_matmul(self):
        rng = np.random.default_rng(2)
        a = jnp.asarray(rng.standard_normal((96, 64)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((64, 80)), jnp.float32)
        out = matmul_int8(a, b, block_m=32, block_n=128, block_k=128)
        ref = np.asarray(a) @ np.asarray(b)
        # W8A8 error: ~1% relative on randn data at K=64
        rel = np.abs(np.asarray(out) - ref) / (np.abs(ref) + 1e-3)
        assert np.median(rel) < 0.02, float(np.median(rel))

    def test_multi_k_block_accumulation(self):
        # K spans several grid steps: the int32 scratch carry must be exact
        rng = np.random.default_rng(3)
        a = jnp.asarray(rng.integers(-3, 4, (32, 512)), jnp.float32)
        b = jnp.asarray(rng.integers(-3, 4, (512, 128)), jnp.float32)
        out = int8_matmul(a.astype(jnp.int8), jnp.ones((32, 1), jnp.float32),
                          b.astype(jnp.int8), jnp.ones((1, 128), jnp.float32),
                          block_m=32, block_n=128, block_k=128)
        ref = np.asarray(a, np.int64) @ np.asarray(b, np.int64)
        np.testing.assert_array_equal(np.asarray(out), ref.astype(np.float32))

    def test_ragged_shapes_pad(self):
        rng = np.random.default_rng(4)
        a = jnp.asarray(rng.standard_normal((37, 45)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((45, 51)), jnp.float32)
        out = matmul_int8(a, b)
        assert out.shape == (37, 51)
        ref = np.asarray(a) @ np.asarray(b)
        rel = np.abs(np.asarray(out) - ref) / (np.abs(ref) + 1e-3)
        assert np.median(rel) < 0.05

    def test_mismatch_raises(self):
        a = jnp.zeros((4, 8), jnp.int8)
        b = jnp.zeros((9, 4), jnp.int8)
        with pytest.raises(ValueError, match="contraction mismatch"):
            int8_matmul(a, jnp.ones((4, 1), jnp.float32),
                        b, jnp.ones((1, 4), jnp.float32))

    def test_bf16_output(self):
        rng = np.random.default_rng(5)
        a = jnp.asarray(rng.standard_normal((32, 32)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((32, 32)), jnp.float32)
        out = matmul_int8(a, b, out_dtype=jnp.bfloat16)
        assert out.dtype == jnp.bfloat16
