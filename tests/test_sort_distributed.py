"""Distributed sort via the odd-even transposition merge-split network
(VERDICT r2 item 5; reference heat/core/manipulations.py:2258-2409 is a
sample-sort over Alltoallv — ours is a static-shape shard_map network)."""

import numpy as np
import pytest

import heat_tpu as ht
from .basic_test import TestCase as BTTestCase


def _check_sort(xn, split, axis=-1, descending=False):
    x = ht.array(xn, split=split)
    v, i = ht.sort(x, axis=axis, descending=descending)
    ref = np.sort(xn, axis=axis, kind="stable")
    if descending:
        ref = np.flip(ref, axis=axis)
    np.testing.assert_array_equal(v.numpy(), ref)
    # indices must reconstruct the values
    np.testing.assert_array_equal(
        np.take_along_axis(xn, i.numpy().astype(np.int64), axis=axis), ref
    )
    assert v.split == x.split and i.split == x.split
    return v, i


class TestDistributedSort:
    def test_1d_nondivisible(self):
        rng = np.random.default_rng(0)
        _check_sort(rng.standard_normal(11).astype(np.float32), split=0, axis=0)

    def test_1d_divisible(self):
        rng = np.random.default_rng(1)
        _check_sort(rng.standard_normal(16).astype(np.float32), split=0, axis=0)

    def test_1d_larger(self):
        rng = np.random.default_rng(2)
        _check_sort(rng.standard_normal(1001).astype(np.float32), split=0, axis=0)

    def test_1d_descending(self):
        rng = np.random.default_rng(3)
        _check_sort(rng.standard_normal(13).astype(np.float32), split=0, axis=0, descending=True)

    def test_ties_stable_indices(self):
        # repeated values: ascending indices must match numpy's stable argsort
        xn = np.array([3, 1, 3, 1, 2, 3, 1, 2, 2, 3, 1], dtype=np.float32)
        x = ht.array(xn, split=0)
        v, i = ht.sort(x, axis=0)
        np.testing.assert_array_equal(i.numpy(), np.argsort(xn, kind="stable"))

    def test_int_dtype(self):
        rng = np.random.default_rng(4)
        _check_sort(rng.integers(-50, 50, size=19).astype(np.int32), split=0, axis=0)

    def test_int_extremes(self):
        info = np.iinfo(np.int32)
        xn = np.array([5, info.max, info.min, 0, info.max, info.min, -1], dtype=np.int32)
        _check_sort(xn, split=0, axis=0)

    def test_bool_dtype(self):
        xn = np.array([True, False, True, True, False, False, True, False, True], dtype=np.bool_)
        _check_sort(xn, split=0, axis=0)

    def test_2d_sort_along_split(self):
        rng = np.random.default_rng(5)
        xn = rng.standard_normal((11, 4)).astype(np.float32)
        _check_sort(xn, split=0, axis=0)

    def test_2d_sort_along_split_descending(self):
        rng = np.random.default_rng(6)
        xn = rng.standard_normal((9, 3)).astype(np.float32)
        _check_sort(xn, split=0, axis=0, descending=True)

    def test_2d_sort_nonsplit_axis_local(self):
        rng = np.random.default_rng(7)
        xn = rng.standard_normal((11, 5)).astype(np.float32)
        _check_sort(xn, split=0, axis=1)

    def test_2d_split1_sort_axis1(self):
        rng = np.random.default_rng(8)
        xn = rng.standard_normal((4, 13)).astype(np.float32)
        _check_sort(xn, split=1, axis=1)

    def test_replicated_sort(self):
        rng = np.random.default_rng(9)
        _check_sort(rng.standard_normal(10).astype(np.float32), split=None, axis=0)

    def test_presorted_and_reversed(self):
        xn = np.arange(17, dtype=np.float32)
        _check_sort(xn, split=0, axis=0)
        _check_sort(xn[::-1].copy(), split=0, axis=0)

    def test_all_equal(self):
        xn = np.full(12, 7.0, dtype=np.float32)
        x = ht.array(xn, split=0)
        v, i = ht.sort(x, axis=0)
        np.testing.assert_array_equal(v.numpy(), xn)
        np.testing.assert_array_equal(i.numpy(), np.arange(12))

    def test_fewer_rows_than_devices(self):
        xn = np.array([2.0, 1.0, 3.0], dtype=np.float32)
        _check_sort(xn, split=0, axis=0)

    def test_sorted_values_stay_distributed(self):
        rng = np.random.default_rng(10)
        xn = rng.standard_normal(64).astype(np.float32)
        x = ht.array(xn, split=0)
        v, _ = ht.sort(x, axis=0)
        if ht.get_comm().size > 1:
            devs = {s.device for s in v.larray.addressable_shards}
            assert len(devs) == ht.get_comm().size


class TestUniqueCeiling:
    """Size pins for unique at/past the old documented ceiling. (Since
    round 5 split inputs — flat AND axis=k — run distributed algorithms;
    these sizes now exercise those paths on the test mesh, plus the eager
    path's host-memory-bound behavior when run single-device.)"""

    def test_unique_documented_ceiling(self):
        n = 1 << 20  # 1,048,576 elements — the documented tested ceiling
        rng = np.random.default_rng(11)
        xn = rng.integers(0, 1000, size=n).astype(np.int32)
        x = ht.array(xn, split=0)
        u = ht.unique(x)
        np.testing.assert_array_equal(np.sort(u.numpy()), np.unique(xn))

    @pytest.mark.slow
    def test_unique_above_ceiling_host_bound_not_failure(self):
        # PARITY.md promises "host-memory-bound, not failure" ABOVE the
        # ceiling — pin that for the eager axis-unique path (r3 weak #7)
        n = (1 << 20) + 4097  # past the ceiling, deliberately not a power of two
        rng = np.random.default_rng(12)
        xn = rng.integers(0, 50, size=2 * n).astype(np.int32).reshape(n, 2)
        u = ht.unique(ht.array(xn, split=0), axis=0)
        np.testing.assert_array_equal(u.numpy(), np.unique(xn, axis=0))

    def test_unique_inverse_roundtrip(self):
        xn = np.array([3, 1, 2, 3, 1, 2, 9], dtype=np.int32)
        x = ht.array(xn, split=0)
        u, inv = ht.unique(x, return_inverse=True)
        np.testing.assert_array_equal(u.numpy()[inv.numpy()], xn)


class TestDistributedUnique:
    """1-D split unique is a real distributed algorithm (sort -> ppermute
    boundary mask -> exscan gids -> scatter+psum compaction); only the
    output size crosses to the host. Oracle: np.unique."""

    def test_matches_numpy(self):
        rng = np.random.default_rng(13)
        xn = rng.integers(0, 50, size=229).astype(np.int32)  # non-divisible n
        x = ht.array(xn, split=0)
        u = ht.unique(x)
        assert u.split == 0
        np.testing.assert_array_equal(u.numpy(), np.unique(xn))

    def test_floats_with_duplicates(self):
        rng = np.random.default_rng(17)
        xn = np.round(rng.standard_normal(500), 1).astype(np.float32)
        u = ht.unique(ht.array(xn, split=0))
        np.testing.assert_allclose(u.numpy(), np.unique(xn))

    def test_all_equal(self):
        xn = np.full(100, 7, dtype=np.int64)
        u = ht.unique(ht.array(xn, split=0))
        np.testing.assert_array_equal(u.numpy(), np.array([7]))

    def test_all_distinct(self):
        xn = np.arange(97, dtype=np.int32)[::-1].copy()
        u = ht.unique(ht.array(xn, split=0))
        np.testing.assert_array_equal(u.numpy(), np.arange(97))

    def test_return_inverse_distributed(self):
        rng = np.random.default_rng(19)
        xn = rng.integers(0, 30, size=171).astype(np.int32)
        x = ht.array(xn, split=0)
        u, inv = ht.unique(x, return_inverse=True)
        np.testing.assert_array_equal(np.asarray(u.numpy())[inv.numpy()], xn)
        np.testing.assert_array_equal(u.numpy(), np.unique(xn))
        assert inv.split == 0 and inv.shape == xn.shape

    def test_output_stays_sharded(self):
        comm = ht.get_comm()
        rng = np.random.default_rng(23)
        xn = rng.integers(0, 1000, size=4096).astype(np.int32)
        u = ht.unique(ht.array(xn, split=0))
        if comm.size > 1:
            devs = {s.device for s in u.larray.addressable_shards}
            assert len(devs) == comm.size

    def test_fewer_uniques_than_devices(self):
        comm = ht.get_comm()
        xn = np.tile(np.array([5, 2], dtype=np.int32), 64)
        u = ht.unique(ht.array(xn, split=0))  # U=2 < p on the 8-mesh
        np.testing.assert_array_equal(u.numpy(), np.array([2, 5]))
        assert u.shape == (2,)

    def test_bool_dtype(self):
        # psum promotes bool — the scatter must round-trip through int
        xn = np.tile(np.array([True, False, True], dtype=bool), 16)
        u = ht.unique(ht.array(xn, split=0))
        assert u.numpy().dtype == np.bool_
        np.testing.assert_array_equal(u.numpy(), np.array([False, True]))

    def test_nan_collapses_like_numpy(self):
        # numpy equal_nan default: one unique NaN, not one per NaN
        xn = np.array([1.0, 2.0] + [np.nan] * 16 + [1.0] * 14, dtype=np.float32)
        u = ht.unique(ht.array(xn, split=0))
        np.testing.assert_array_equal(u.numpy(), np.unique(xn))

    def test_nan_with_tail_pads(self):
        # non-divisible n: NaNs sort past the +inf pad fill — the valid mask
        # must come from original indices, not sorted position
        xn = np.array([3.0, np.nan, 1.0, np.nan, 2.0, 1.0, np.nan], dtype=np.float64)
        u = ht.unique(ht.array(xn, split=0))
        np.testing.assert_array_equal(u.numpy(), np.unique(xn))


class TestDistributedRowUnique(BTTestCase):
    """unique(a, axis=k) on split arrays is a distributed algorithm
    (VERDICT r4 item 4): lexicographic odd-even row sort -> neighbor
    row-equality mask -> row compaction; no host gather, no size ceiling.
    Oracle: np.unique(axis=k)."""

    def _check(self, xn, axis, split):
        x = ht.array(xn, split=split)
        u = ht.unique(x, axis=axis)
        np.testing.assert_array_equal(u.numpy(), np.unique(xn, axis=axis))
        uv, inv = ht.unique(x, axis=axis, return_inverse=True)
        wv, winv = np.unique(xn, axis=axis, return_inverse=True)
        np.testing.assert_array_equal(inv.numpy(), winv)
        np.testing.assert_array_equal(uv.numpy(), wv)

    @pytest.mark.slow
    def test_axis0_all_splits(self):
        rng = np.random.default_rng(29)
        xn = rng.integers(0, 4, (4 * self.comm.size + 3, 3)).astype(np.float32)
        for split in (0, 1):
            self._check(xn, 0, split)

    @pytest.mark.slow
    def test_axis1_all_splits(self):
        rng = np.random.default_rng(31)
        xn = rng.integers(0, 2, (4, 3 * self.comm.size + 1)).astype(np.int64)
        for split in (0, 1):
            self._check(xn, 1, split)

    @pytest.mark.slow
    def test_3d_axis0(self):
        rng = np.random.default_rng(37)
        xn = rng.integers(0, 3, (2 * self.comm.size + 5, 2, 2)).astype(np.int32)
        for split in (0, 2):
            self._check(xn, 0, split)

    def test_rows_stay_sharded(self):
        comm = ht.get_comm()
        rng = np.random.default_rng(41)
        xn = rng.integers(0, 40, (64 * comm.size, 2)).astype(np.int32)
        u = ht.unique(ht.array(xn, split=0), axis=0)
        assert u.split == 0
        if comm.size > 1:
            devs = {s.device for s in u.larray.addressable_shards}
            assert len(devs) == comm.size

    def test_all_rows_equal(self):
        xn = np.tile(np.array([[2, 7]], dtype=np.int64), (50, 1))
        u = ht.unique(ht.array(xn, split=0), axis=0)
        np.testing.assert_array_equal(u.numpy(), np.array([[2, 7]]))

    def test_nan_rows_like_numpy(self):
        # numpy equal_nan default applies elementwise to rows
        xn = np.array(
            [[1.0, np.nan], [1.0, np.nan], [np.nan, 2.0], [1.0, 2.0], [1.0, 2.0]],
            dtype=np.float64,
        )
        u = ht.unique(ht.array(xn, split=0), axis=0)
        np.testing.assert_array_equal(u.numpy(), np.unique(xn, axis=0))

    def test_1d_axis0_nan_distinct(self):
        # axis= semantics on 1-D input: NaNs stay DISTINCT (np.unique with
        # axis=0 compares structured fields, NaN != NaN) — unlike the flat
        # path's equal_nan collapse
        xn = np.array([np.nan, 1.0, np.nan, 2.0, 1.0], dtype=np.float64)
        u = ht.unique(ht.array(xn, split=0), axis=0)
        np.testing.assert_array_equal(u.numpy(), np.unique(xn, axis=0))
        uf = ht.unique(ht.array(xn, split=0))  # flat: one NaN
        np.testing.assert_array_equal(uf.numpy(), np.unique(xn))

    @pytest.mark.slow
    def test_randomized_oracle_sweep(self):
        # deterministic randomized configs: shapes x dtypes x axes x splits
        rng = np.random.default_rng(97)
        dtypes = (np.int32, np.int64, np.float32, np.float64)
        for trial in range(12):
            ndim = int(rng.integers(2, 4))
            shape = tuple(int(rng.integers(2, 14)) for _ in range(ndim))
            axis = int(rng.integers(0, ndim))
            split = int(rng.integers(0, ndim))
            dt = dtypes[trial % len(dtypes)]
            vals = rng.integers(0, 3, shape).astype(dt)
            x = ht.array(vals, split=split)
            got = ht.unique(x, axis=axis)
            want = np.unique(vals, axis=axis)
            np.testing.assert_array_equal(
                got.numpy(), want,
                err_msg=f"trial={trial} shape={shape} axis={axis} split={split} {dt}",
            )
            gv, gi = ht.unique(x, axis=axis, return_inverse=True)
            wv, wi = np.unique(vals, axis=axis, return_inverse=True)
            np.testing.assert_array_equal(gi.numpy(), wi)

    @pytest.mark.slow
    def test_past_old_ceiling(self):
        # 2.1M rows — past the old 2^20 eager-path ceiling (VERDICT r4)
        rng = np.random.default_rng(43)
        xn = rng.integers(0, 800, ((1 << 21) + 17, 2)).astype(np.int32)
        u = ht.unique(ht.array(xn, split=0), axis=0)
        np.testing.assert_array_equal(u.numpy(), np.unique(xn, axis=0))


class TestUniqueNDim(BTTestCase):
    """n-D unique with axis=None relayouts once to a flat split=0 vector
    and runs the distributed algorithm; inverses come back input-shaped
    (numpy semantics)."""

    @pytest.mark.slow
    def test_matrix_and_3d(self):
        rng = np.random.default_rng(161)
        for shape in ((2 * self.comm.size + 1, 4), (3, self.comm.size + 2, 2)):
            t = rng.integers(0, 7, shape)
            for split in (0, 1):
                x = ht.array(t, split=split)
                u, inv = ht.unique(x, return_inverse=True)
                np.testing.assert_array_equal(
                    np.sort(u.numpy()), np.unique(t), err_msg=f"{shape} {split}"
                )
                assert inv.shape == t.shape
                np.testing.assert_array_equal(u.numpy()[inv.numpy()], t)
