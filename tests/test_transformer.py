"""Tests for heat_tpu.nn transformer blocks.

Oracle strategy: every attention impl ("local", "flash", "ring",
"ulysses") must produce the same block output from the same params — the
impl switch changes the schedule, never the math (SURVEY §4 pattern:
distributed result == replicated computation).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.nn import TransformerBlock, TransformerLM


@pytest.fixture(scope="module")
def comm():
    return ht.get_comm()


def _block_out(impl, x, comm=None, seed=0):
    blk = TransformerBlock(num_heads=4, attn_impl=impl, comm=comm, block_size=16)
    params = blk.init(jax.random.PRNGKey(seed), x)
    return blk.apply(params, x), params


class TestTransformerBlock:
    def test_impls_agree_single_shard(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 32, 32)), jnp.float32)
        blk_l = TransformerBlock(num_heads=4, attn_impl="local", block_size=16)
        params = blk_l.init(jax.random.PRNGKey(0), x)
        out_l = blk_l.apply(params, x)
        blk_f = TransformerBlock(num_heads=4, attn_impl="flash")
        out_f = blk_f.apply(params, x)  # same params: same math
        np.testing.assert_allclose(np.asarray(out_l), np.asarray(out_f),
                                   rtol=2e-5, atol=2e-5)

    def test_sequence_parallel_agree(self, comm):
        p = comm.size
        rng = np.random.default_rng(1)
        t = 8 * p
        x = jnp.asarray(rng.standard_normal((2, t, 4 * p)), jnp.float32)
        blk_l = TransformerBlock(num_heads=p, attn_impl="local", block_size=8)
        params = blk_l.init(jax.random.PRNGKey(1), x)
        out_l = blk_l.apply(params, x)
        xs = jax.device_put(x, comm.sharding(1, 3))
        for impl in ("ring", "ulysses"):
            blk = TransformerBlock(num_heads=p, attn_impl=impl, comm=comm)
            out = blk.apply(params, xs)
            np.testing.assert_allclose(np.asarray(out), np.asarray(out_l),
                                       rtol=2e-4, atol=2e-4)

    def test_grads_flow_every_impl(self):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((1, 16, 16)), jnp.float32)
        blk = TransformerBlock(num_heads=2, attn_impl="local", block_size=8)
        params = blk.init(jax.random.PRNGKey(2), x)

        grads = {}
        for impl in ("local", "flash"):
            b = TransformerBlock(num_heads=2, attn_impl=impl, block_size=8)
            g = jax.grad(lambda p, b=b: b.apply(p, x).sum())(params)
            grads[impl] = g
        fl = jax.tree_util.tree_leaves(grads["local"])
        ff = jax.tree_util.tree_leaves(grads["flash"])
        for a, b_ in zip(fl, ff):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=5e-4, atol=5e-4)

    @pytest.mark.slow
    def test_sequence_parallel_grads_match_local(self, comm):
        # the ring/ulysses backward re-runs the schedule under autodiff —
        # gradients must match the single-shard oracle, not just the forward
        p = comm.size
        rng = np.random.default_rng(7)
        t = 8 * p
        x = jnp.asarray(rng.standard_normal((1, t, 4 * p)), jnp.float32)
        blk_l = TransformerBlock(num_heads=p, attn_impl="local", block_size=8)
        params = blk_l.init(jax.random.PRNGKey(7), x)
        g_ref = jax.grad(lambda pr: (blk_l.apply(pr, x) ** 2).sum())(params)
        xs = jax.device_put(x, comm.sharding(1, 3))
        for impl in ("ring", "ulysses"):
            blk = TransformerBlock(num_heads=p, attn_impl=impl, comm=comm)
            g = jax.grad(lambda pr, blk=blk: (blk.apply(pr, xs) ** 2).sum())(params)
            for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                            jax.tree_util.tree_leaves(g)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-3, atol=2e-3)

    def test_lm_rejects_overlong_sequence(self):
        lm = TransformerLM(vocab_size=11, d_model=16, num_heads=2, num_layers=1,
                           max_len=8, attn_impl="local")
        toks = jnp.zeros((1, 16), jnp.int32)
        with pytest.raises(ValueError, match="exceeds max_len"):
            lm.init(jax.random.PRNGKey(0), toks)

    def test_bad_heads_raises(self):
        x = jnp.zeros((1, 8, 30))
        blk = TransformerBlock(num_heads=4)
        with pytest.raises(ValueError, match="not divisible"):
            blk.init(jax.random.PRNGKey(0), x)


class TestTransformerLM:
    def test_forward_shapes_and_finite(self):
        lm = TransformerLM(vocab_size=50, d_model=32, num_heads=4, num_layers=2,
                           max_len=64, attn_impl="local", block_size=16)
        toks = jnp.arange(48).reshape(2, 24) % 50
        params = lm.init(jax.random.PRNGKey(3), toks)
        logits = lm.apply(params, toks)
        assert logits.shape == (2, 24, 50)
        assert np.isfinite(np.asarray(logits)).all()

    def test_causality(self):
        # changing a future token must not change earlier logits
        lm = TransformerLM(vocab_size=17, d_model=16, num_heads=2, num_layers=1,
                           max_len=32, attn_impl="local", block_size=8)
        toks = jnp.arange(16).reshape(1, 16) % 17
        params = lm.init(jax.random.PRNGKey(4), toks)
        base = lm.apply(params, toks)
        toks2 = toks.at[0, -1].set((toks[0, -1] + 1) % 17)
        pert = lm.apply(params, toks2)
        np.testing.assert_allclose(np.asarray(base[0, :-1]),
                                   np.asarray(pert[0, :-1]), rtol=1e-6, atol=1e-6)

    def test_train_step_decreases_loss(self):
        import optax

        lm = TransformerLM(vocab_size=11, d_model=16, num_heads=2, num_layers=1,
                           max_len=32, attn_impl="local", block_size=8)
        rng = np.random.default_rng(5)
        toks = jnp.asarray(rng.integers(0, 11, (4, 12)))
        params = lm.init(jax.random.PRNGKey(5), toks)
        opt = optax.adam(1e-2)
        state = opt.init(params)

        def loss_fn(p):
            logits = lm.apply(p, toks[:, :-1])
            tgt = toks[:, 1:]
            return optax.softmax_cross_entropy_with_integer_labels(logits, tgt).mean()

        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(loss_fn)(p)
            u, s = opt.update(g, s)
            return optax.apply_updates(p, u), s, l

        l0 = None
        for _ in range(10):
            params, state, l = step(params, state)
            l0 = l0 if l0 is not None else float(l)
        assert float(l) < l0


class TestRemat:
    @pytest.mark.slow
    def test_remat_same_numerics_and_grads(self):
        import optax

        lm = TransformerLM(vocab_size=17, d_model=16, num_heads=2, num_layers=2,
                           max_len=32, attn_impl="local", block_size=8)
        lm_r = TransformerLM(vocab_size=17, d_model=16, num_heads=2, num_layers=2,
                             max_len=32, attn_impl="local", block_size=8,
                             remat=True)
        toks = jnp.arange(32).reshape(2, 16) % 17
        params = lm.init(jax.random.PRNGKey(11), toks)
        np.testing.assert_allclose(
            np.asarray(lm.apply(params, toks)),
            np.asarray(lm_r.apply(params, toks)), rtol=1e-6, atol=1e-6,
        )

        def loss(m):
            def f(p):
                logits = m.apply(p, toks[:, :-1])
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, toks[:, 1:]
                ).mean()
            return f

        g = jax.grad(loss(lm))(params)
        gr = jax.grad(loss(lm_r))(params)
        for a, b in zip(jax.tree_util.tree_leaves(g),
                        jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
