"""Systematic getitem/setitem key sweeps vs the numpy oracle (reference
dndarray.py:661-1549 resolves each key family with its own split-rule
calculus; here one table drives every family across splits)."""

import numpy as np
import pytest

import heat_tpu as ht
from .basic_test import TestCase


def _keys_2d(n, m):
    """Key table covering every family the reference handles for a 2-D
    array: ints, slices (incl. steps/negatives), ellipsis, newaxis,
    boolean masks, integer arrays, and mixed tuples."""
    rng = np.random.default_rng(7)
    mask_rows = rng.random(n) > 0.5
    mask_full = rng.random((n, m)) > 0.5
    idx = np.asarray([0, n - 1, 1, 0])
    return [
        2,
        -1,
        slice(None),
        slice(1, n - 1),
        slice(None, None, 2),
        slice(None, None, -1),
        (slice(None), 1),
        (slice(None), slice(1, m)),
        (slice(None), slice(None, None, -1)),
        Ellipsis,
        (Ellipsis, 0),
        (1, Ellipsis),
        (None, slice(None)),
        (slice(None), None, slice(None)),
        mask_rows,
        mask_full,
        idx,
        (idx, slice(None)),
        (slice(None), np.asarray([0, m - 1])),
        (idx, np.asarray([0, 1, 2, 0]) % m),
        (slice(1, None), np.asarray([0, 1]) % m),
    ]


class TestGetitemSweep(TestCase):
    def test_every_key_every_split(self):
        p = self.comm.size
        n, m = p + 3, 5
        base = np.arange(n * m, dtype=np.float32).reshape(n, m)
        for split in (None, 0, 1):
            x = ht.array(base, split=split)
            for key in _keys_2d(n, m):
                want = base[key]
                got = x[key]
                if isinstance(got, ht.DNDarray) and got.ndim:
                    self.assert_array_equal(got, want)
                else:
                    np.testing.assert_allclose(np.asarray(got), want)

    def test_1d_key_sweep(self):
        p = self.comm.size
        n = 3 * p + 2
        a = np.arange(n, dtype=np.float32)
        keys = [
            0, n - 1, -2,
            slice(2, None), slice(None, -2), slice(None, None, 3),
            slice(n, None), slice(-1, None, -2),
            np.asarray([0, n - 1, n // 2]),
            a > (n / 2),
        ]
        for split in (None, 0):
            x = ht.array(a, split=split)
            for key in keys:
                want = a[key]
                got = x[key]
                if isinstance(got, ht.DNDarray) and got.ndim:
                    self.assert_array_equal(got, want)
                else:
                    np.testing.assert_allclose(np.asarray(got), want)

    def test_3d_partial_keys(self):
        t = np.arange(2 * 3 * 4, dtype=np.float32).reshape(2, 3, 4)
        for split in (None, 0, 1, 2):
            x = ht.array(t, split=split)
            for key in [1, (slice(None), 2), (0, slice(None), slice(1, 3)),
                        (Ellipsis, 1), (slice(None), slice(None), -1)]:
                want = t[key]
                got = x[key]
                if isinstance(got, ht.DNDarray) and got.ndim:
                    self.assert_array_equal(got, want)
                else:
                    np.testing.assert_allclose(np.asarray(got), want)

    def test_empty_result_slices(self):
        a = np.arange(10, dtype=np.float32)
        x = ht.array(a, split=0)
        got = x[5:5]
        assert tuple(got.shape) == (0,)
        got = x[8:2]
        assert tuple(got.shape) == (0,)


class TestSetitemSweep(TestCase):
    def _roundtrip(self, base, split, key, value):
        want = base.copy()
        want[key] = value
        x = ht.array(base.copy(), split=split)
        x[key] = value
        self.assert_array_equal(x, want)

    def test_scalar_values_every_key(self):
        p = self.comm.size
        n, m = p + 2, 4
        base = np.arange(n * m, dtype=np.float32).reshape(n, m)
        keys = [
            1, -1, slice(1, n - 1), (slice(None), 2),
            (slice(None), slice(0, 2)), slice(None, None, 2),
            (0, 0), Ellipsis,
        ]
        for split in (None, 0, 1):
            for key in keys:
                self._roundtrip(base, split, key, -9.0)

    def test_array_values(self):
        p = self.comm.size
        n, m = p + 2, 4
        base = np.zeros((n, m), dtype=np.float32)
        row = np.arange(m, dtype=np.float32)
        col = np.arange(n, dtype=np.float32)
        block = np.ones((n - 2, m), dtype=np.float32) * 5
        for split in (None, 0, 1):
            self._roundtrip(base, split, 0, row)
            self._roundtrip(base, split, (slice(None), 1), col)
            self._roundtrip(base, split, slice(1, n - 1), block)

    def test_broadcast_value_into_slice(self):
        p = self.comm.size
        base = np.zeros((p + 2, 3), dtype=np.float32)
        for split in (None, 0, 1):
            self._roundtrip(base, split, slice(None), np.arange(3, dtype=np.float32))

    def test_int_array_key_set(self):
        p = self.comm.size
        n = 2 * p + 3
        base = np.zeros(n, dtype=np.float32)
        idx = np.asarray([0, n - 1, n // 2])
        for split in (None, 0):
            self._roundtrip(base, split, idx, 7.0)

    def test_bool_mask_set_full_shape(self):
        p = self.comm.size
        base = np.arange(p + 4, dtype=np.float32)
        mask = base % 2 == 0
        for split in (None, 0):
            self._roundtrip(base, split, mask, 0.0)

    def test_setitem_dndarray_value_cross_split(self):
        p = self.comm.size
        n = p + 2
        base = np.zeros((n, 3), dtype=np.float32)
        val = np.ones((n, 3), dtype=np.float32) * 4
        want = val.copy()
        for split in (None, 0, 1):
            for vsplit in (None, 0):
                x = ht.array(base.copy(), split=split)
                x[:] = ht.array(val, split=vsplit)
                self.assert_array_equal(x, want)

    def test_setitem_preserves_dtype(self):
        x = ht.zeros((4,), dtype=ht.int32, split=0)
        x[1] = 7
        assert x.dtype == ht.int32
        np.testing.assert_array_equal(x.numpy(), [0, 7, 0, 0])


class TestWhereNonzeroDeep(TestCase):
    def test_where_three_arg_splits(self):
        p = self.comm.size
        rng = np.random.default_rng(8)
        a = rng.standard_normal((p + 1, 3)).astype(np.float32)
        b = np.zeros_like(a)
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            y = ht.array(b, split=split)
            got = ht.where(x > 0, x, y)
            self.assert_array_equal(got, np.where(a > 0, a, b))

    def test_where_scalar_branches(self):
        a = np.asarray([-1.0, 0.0, 2.0], dtype=np.float32)
        x = ht.array(a, split=0)
        got = ht.where(x > 0, ht.ones_like(x), ht.zeros_like(x))
        self.assert_array_equal(got, np.where(a > 0, 1.0, 0.0))

    def test_nonzero_empty_and_full(self):
        z = np.zeros((2, 3), dtype=np.float32)
        f = np.ones((2, 3), dtype=np.float32)
        for split in (None, 0):
            got_z = ht.nonzero(ht.array(z, split=split))
            assert got_z.shape[0] == 0
            got_f = ht.nonzero(ht.array(f, split=split))
            assert got_f.shape[0] == 6

    def test_nonzero_matches_numpy_order(self):
        rng = np.random.default_rng(9)
        m = (rng.random((self.comm.size + 1, 4)) > 0.6).astype(np.float32)
        for split in (None, 0, 1):
            got = ht.nonzero(ht.array(m, split=split)).numpy()
            want = np.stack(np.nonzero(m), axis=1)
            np.testing.assert_array_equal(np.asarray(got), want)


class TestViewSemantics(TestCase):
    """The physical fast paths must not alias mutable state across
    DNDarrays (jax arrays are immutable — the framework contract is
    copy-on-write everywhere, unlike the reference's torch views)."""

    def test_getitem_result_independent(self):
        a = np.arange(8, dtype=np.float32)
        x = ht.array(a, split=0)
        y = x[2:6]
        x[3] = 99.0
        np.testing.assert_array_equal(y.numpy(), a[2:6])

    def test_setitem_does_not_leak_to_copy(self):
        a = np.arange(8, dtype=np.float32)
        x = ht.array(a, split=0)
        y = ht.array(a, split=0)
        x[0] = -5.0
        np.testing.assert_array_equal(y.numpy(), a)


class TestDistributedNonzero(TestCase):
    """nonzero on split=0 inputs is a distributed compaction (mask →
    distributed cumsum → sharded scatter): only the scalar nnz reaches the
    host, results stay split=0 in numpy row-major order."""

    def _nlog(self):
        from heat_tpu.core.dndarray import _PERF_STATS

        return _PERF_STATS["logical_slices"]

    def test_no_gather_and_numpy_order(self):
        rng = np.random.default_rng(111)
        for shape in ((5 * self.comm.size + 3,), (3 * self.comm.size + 1, 4)):
            t = rng.standard_normal(shape)
            t[t < 0.3] = 0.0
            x = ht.array(t, split=0)
            c0 = self._nlog()
            r = ht.nonzero(x)
            assert self._nlog() == c0
            assert r.split == 0
            np.testing.assert_array_equal(r.numpy(), np.stack(np.nonzero(t), axis=1))

    def test_empty_full_and_fallbacks(self):
        p = self.comm.size
        assert ht.nonzero(ht.zeros((3 * p,), split=0)).shape == (0, 1)
        np.testing.assert_array_equal(
            ht.nonzero(ht.ones((2 * p + 1,), split=0)).numpy(),
            np.arange(2 * p + 1)[:, None],
        )
        rng = np.random.default_rng(112)
        t = rng.standard_normal((4, 2 * p))
        t[t < 0] = 0
        for split in (None, 1):
            np.testing.assert_array_equal(
                ht.nonzero(ht.array(t, split=split)).numpy(),
                np.stack(np.nonzero(t), axis=1),
            )

    def test_where_one_arg_routes_through(self):
        p = self.comm.size
        a = np.arange(3 * p, dtype=np.float32) - p
        got = ht.where(ht.array(a, split=0) > 0)
        np.testing.assert_array_equal(got.numpy(), np.stack(np.nonzero(a > 0), axis=1))


class TestDistributedMaskedSelect(TestCase):
    """x[mask] with a full-shape boolean DNDarray mask on split=0 data runs
    the distributed compaction — neither data nor mask gathers; only the
    scalar nnz reaches the host."""

    def _nlog(self):
        from heat_tpu.core.dndarray import _PERF_STATS

        return _PERF_STATS["logical_slices"]

    def test_no_gather_order_and_split(self):
        rng = np.random.default_rng(113)
        for shape in ((5 * self.comm.size + 3,), (2 * self.comm.size + 1, 4)):
            t = rng.standard_normal(shape).astype(np.float32)
            x = ht.array(t, split=0)
            c0 = self._nlog()
            r = x[x > 0.2]
            assert self._nlog() == c0
            assert r.split == 0
            np.testing.assert_array_equal(r.numpy(), t[t > 0.2])

    def test_replicated_mask_empty_and_full(self):
        rng = np.random.default_rng(114)
        t = rng.standard_normal(4 * self.comm.size + 1).astype(np.float32)
        x = ht.array(t, split=0)
        m = ht.array(t > 0, split=None)
        np.testing.assert_array_equal(x[m].numpy(), t[t > 0])
        np.testing.assert_array_equal(x[x > 1e9].numpy(), t[t > 1e9])
        np.testing.assert_array_equal(x[x < 1e9].numpy(), t[t < 1e9])


class TestMixedAdvancedShardSide(TestCase):
    """Round-4 (VERDICT r3 item 6): (slice, int-array) and
    (int-array, int-array) key patterns stay shard-side — sharded gather,
    no replicated intermediate, no host-logical view."""

    def _np_oracle(self, shape, key, split):
        xn = np.arange(np.prod(shape), dtype=np.float32).reshape(shape)
        x = ht.array(xn, split=split)
        from heat_tpu.core.dndarray import _PERF_STATS

        before = _PERF_STATS["logical_slices"]
        got = x[key]
        assert _PERF_STATS["logical_slices"] == before, "hit the logical view"
        np.testing.assert_array_equal(got.numpy(), xn[key])
        return got

    def test_slice_then_array(self):
        idx = np.array([5, 0, 3, 3, 6])
        for split in (0, 1):
            got = self._np_oracle((11, 7), (slice(2, 6), idx), split)
            assert got.shape == (4, 5)

    def test_array_then_slice(self):
        idx = np.array([5, 0, 3])
        for split in (0, 1):
            self._np_oracle((11, 7), (idx, slice(1, 4)), split)

    def test_int_and_array_consecutive(self):
        idx = np.array([2, 4, 0])
        for split in (0, 1):
            got = self._np_oracle((11, 7), (3, idx), split)
            assert got.shape == (3,)
        got = self._np_oracle((11, 7, 5), (slice(None), 2, idx), 0)
        assert got.shape == (11, 3)

    def test_paired_arrays(self):
        rows = np.array([1, 5, 9, 0])
        cols = np.array([0, 3, 6, 2])
        for split in (0, 1):
            got = self._np_oracle((11, 7), (rows, cols), split)
            assert got.shape == (4,)
            # the result is laid out with its canonical sharding
            import jax

            if got.split is not None:
                assert got.larray.sharding.is_equivalent_to(
                    got.comm.sharding(got.split, got.ndim), got.ndim
                )

    def test_paired_arrays_3d_rest_slice(self):
        rows = np.array([1, 5, 9])
        cols = np.array([0, 3, 6])
        got = self._np_oracle((11, 7, 4), (rows, cols), 0)
        assert got.shape == (3, 4)
        got = self._np_oracle((4, 11, 7), (slice(None), rows, cols), 1)
        assert got.shape == (4, 3)

    def test_paired_negative_indices(self):
        rows = np.array([-1, 0, -11])
        cols = np.array([-7, 3, 0])
        self._np_oracle((11, 7), (rows, cols), 0)

    def test_paired_broadcast_scalar(self):
        rows = np.array([3])
        cols = np.array([0, 1, 2, 6])
        for split in (0, 1):
            self._np_oracle((11, 7), (rows, cols), split)

    def test_separated_advanced_falls_back_correct(self):
        # x[1, :, idx] — separated advanced dims move to the FRONT in
        # numpy; the shard-side decomposition must NOT claim this pattern
        xn = np.arange(11 * 5 * 7, dtype=np.float32).reshape(11, 5, 7)
        x = ht.array(xn, split=0)
        idx = np.array([2, 0, 5])
        got = x[1, :, idx]
        np.testing.assert_array_equal(got.numpy(), xn[1, :, idx])

    def test_out_of_bounds_raises(self):
        x = ht.array(np.zeros((6, 4), np.float32), split=0)
        with self.assertRaises(IndexError):
            x[np.array([0, 6]), np.array([0, 1])]
        with self.assertRaises(IndexError):
            x[slice(0, 3), np.array([4])]
