"""Full FSDP tests (ISSUE 18 tentpole): knob-off replicated dispatch,
enabled-vs-replicated parity, prefetch-depth bit-identity, strictly
lower per-device memory, telemetry pricing + HLO audit of the gathers,
logical checkpoints, ZeRO composition, and the sharded-array checkpoint
kind."""

import json
import os

import flax.linen as fnn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import heat_tpu as ht
from heat_tpu import resilience
from heat_tpu import telemetry as tm
from heat_tpu.core import program_cache
from heat_tpu.nn.fsdp import FSDP
from heat_tpu.optim import ZeroOptimizer
from heat_tpu.parallel import fsdp as F
from heat_tpu.telemetry import collectives as costs
from heat_tpu.telemetry import hlo


@pytest.fixture(scope="module")
def comm():
    return ht.get_comm()


# module-level stages / loss / optimizer: stable identities keep the
# fsdp_train_step program-cache key constant across tests (the
# zero-steady-compile property depends on it)
STAGES = (fnn.Dense(24), fnn.Dense(24), fnn.Dense(4))
OPT = optax.adam(1e-2)


def _loss(out, y):
    return jnp.mean((out - y) ** 2)


def _data(seed=0, batch=8, d=8):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((batch, d)).astype(np.float32)
    y = rng.standard_normal((batch, 4)).astype(np.float32)
    return x, y


def _make(monkeypatch, enabled, **kw):
    monkeypatch.setenv("HEAT_TPU_FSDP", "1" if enabled else "0")
    return FSDP(list(STAGES), optimizer=OPT, **kw)


def _init_logical(model):
    x, _ = _data()
    return model.init(jax.random.PRNGKey(0), x)


def _run(model, steps=3):
    x, y = _data()
    params = model.shard_params(_init_logical(model))
    state = model.init_opt_state(params)
    step = model.make_train_step(_loss)
    xb, yb = model.shard_batch(x, y)
    losses = []
    for _ in range(steps):
        params, state, loss = step(params, state, xb, yb)
        losses.append(float(loss))
    return model.unshard_params(params), losses


def _leaves(tree):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(tree)]


class TestKnobOffDispatch:
    def test_off_matches_dataparallel_bitwise(self, comm, monkeypatch):
        """HEAT_TPU_FSDP=0 must be the replicated DataParallel program
        family, bit-for-bit — the knob is a pure opt-in."""
        off = _make(monkeypatch, enabled=False)
        p_off, l_off = _run(off)

        def full_forward(params, x):
            for m, sp in zip(STAGES, params):
                x = m.apply(sp, x)
            return x

        def dp_loss(params, x, y):
            return _loss(full_forward(params, x), y)

        dp = ht.nn.DataParallel(
            full_forward, comm, OPT, blocking_parameter_updates=True
        )
        x, y = _data()
        params = jax.device_put(_init_logical(off), comm.replicated())
        state = jax.device_put(OPT.init(params), comm.replicated())
        step = dp.make_train_step(dp_loss)
        xb, yb = dp.shard_batch(x, y)
        losses = []
        for _ in range(3):
            params, state, loss = step(params, state, xb, yb)
            losses.append(float(loss))
        assert losses == l_off
        for a, b in zip(_leaves(params), _leaves(p_off)):
            assert np.array_equal(a, b)

    def test_off_params_stay_replicated(self, comm, monkeypatch):
        off = _make(monkeypatch, enabled=False)
        params = off.shard_params(_init_logical(off))
        for l in jax.tree_util.tree_leaves(params):
            assert l.sharding.is_fully_replicated


class TestParity:
    def test_enabled_matches_replicated_within_ulp(self, comm, monkeypatch):
        """Exact-wire FSDP vs the replicated baseline: same math, but
        the gradient reduction runs as a reduce-scatter instead of one
        fused psum, so summation order differs — measured trajectory
        drift is ~1e-9 over 3 adam steps; the documented-ulp bound the
        CI gate also pins is 1e-6."""
        _, l_off = _run(_make(monkeypatch, enabled=False))
        p_off, _ = _run(_make(monkeypatch, enabled=False))
        p_on, l_on = _run(_make(monkeypatch, enabled=True))
        np.testing.assert_allclose(l_on, l_off, rtol=0, atol=1e-6)
        for a, b in zip(_leaves(p_on), _leaves(p_off)):
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-6)

    def test_forward_matches_replicated(self, comm, monkeypatch):
        x, _ = _data()
        off = _make(monkeypatch, enabled=False)
        logical = _init_logical(off)
        ref = off(jax.device_put(logical, comm.replicated()), x)
        on = _make(monkeypatch, enabled=True)
        got = on(on.shard_params(logical), x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=0, atol=1e-6
        )


class TestPrefetchBitIdentity:
    def test_depths_are_pure_scheduling(self, comm, monkeypatch):
        """Prefetch depth changes WHEN gathers are issued, never what
        they compute: trajectories at depths 0/1/2 are bit-identical."""
        runs = [
            _run(_make(monkeypatch, enabled=True, prefetch=d))
            for d in (0, 1, 2)
        ]
        (p0, l0), (p1, l1), (p2, l2) = runs
        assert l0 == l1 == l2
        for a, b, c in zip(_leaves(p0), _leaves(p1), _leaves(p2)):
            assert np.array_equal(a, b) and np.array_equal(a, c)

    def test_negative_depth_rejected(self, comm, monkeypatch):
        with pytest.raises(ValueError, match="prefetch"):
            _make(monkeypatch, enabled=True, prefetch=-1)


class TestMemory:
    def test_sharded_params_strictly_below_replicated(self, comm, monkeypatch):
        p = comm.size
        on = _make(monkeypatch, enabled=True)
        logical = _init_logical(on)
        replicated = jax.device_put(logical, comm.replicated())
        sharded = on.shard_params(logical)
        rb = on.param_bytes_per_device(replicated)
        fb = on.param_bytes_per_device(sharded)
        assert 0 < fb < rb
        # 1/p of the payload plus at most one padding row per leaf
        n_leaves = len(jax.tree_util.tree_leaves(logical))
        assert fb <= rb // p + n_leaves * 4 * p

    def test_opt_state_strictly_below_replicated(self, comm, monkeypatch):
        on = _make(monkeypatch, enabled=True)
        logical = _init_logical(on)
        sharded = on.shard_params(logical)
        state_sharded = on.init_opt_state(sharded)
        state_rep = jax.device_put(
            OPT.init(jax.device_put(logical, comm.replicated())),
            comm.replicated(),
        )
        assert (
            0
            < F.bytes_per_device(state_sharded)
            < F.bytes_per_device(state_rep)
        )


class TestZeroSteadyCompiles:
    def test_train_step_site_stops_missing(self, comm, monkeypatch):
        on = _make(monkeypatch, enabled=True)
        x, y = _data()
        params = on.shard_params(_init_logical(on))
        state = on.init_opt_state(params)
        step = on.make_train_step(_loss)
        xb, yb = on.shard_batch(x, y)
        params, state, _ = step(params, state, xb, yb)  # warm
        misses0 = program_cache.site_stats("fsdp_train_step")["misses"]
        for _ in range(3):
            params, state, _ = step(params, state, xb, yb)
        again = on.make_train_step(_loss)
        assert again is step  # same program object back from the cache
        after = program_cache.site_stats("fsdp_train_step")
        assert after["misses"] == misses0
        assert after["hits"] >= 1


class TestTelemetryPricing:
    def test_gather_and_scatter_events_priced(self, comm, monkeypatch, tmp_path):
        """Each traced fsdp_gather / fsdp_scatter event carries the cost
        model's figure for exactly that leaf (trace-time only — a hot
        cached program emits nothing)."""
        p = comm.size
        reg = tm.enable(str(tmp_path / "ev.jsonl"))
        reg.clear()
        try:
            # unique widths → unique plan signature → guaranteed fresh trace
            stages = [fnn.Dense(20), fnn.Dense(4)]
            monkeypatch.setenv("HEAT_TPU_FSDP", "1")
            model = FSDP(stages, optimizer=OPT)
            x, y = _data()
            params = model.shard_params(model.init(jax.random.PRNGKey(1), x))
            state = model.init_opt_state(params)
            step = model.make_train_step(_loss)
            step(params, state, *model.shard_batch(x, y))
            evs = [e for e in reg.events if e["kind"] == "collective_trace"]
            gathers = [e for e in evs if e["name"] == "fsdp_gather"]
            scatters = [e for e in evs if e["name"] == "fsdp_scatter"]
            assert gathers and scatters
            plan = model._plan
            by_path = {l.path: l for l in plan.leaves}
            for e in gathers:
                leaf = by_path[e["path"]]
                want = costs.fsdp_gather_cost(
                    leaf.chunk, 4, 1, p, e["wire"]
                )
                assert e["bytes"] == want.bytes
                assert e["collective"] == want.kind
            for e in scatters:
                leaf = by_path[e["path"]]
                want = costs.fsdp_scatter_cost(
                    p * leaf.chunk, 4, 1, p, e["wire"]
                )
                assert e["bytes"] == want.bytes
        finally:
            tm.disable()
            reg.clear()


class TestAuditZeroDrift:
    def _leaf(self, comm, chunk=6, wire="off"):
        p = comm.size
        return F.FsdpLeaf(
            path="w", shape=(p * chunk,), dtype="float32",
            sharded=True, wire=wire, chunk=chunk, rule=0,
        )

    def test_flat_gather_audit_matches_cost(self, comm):
        """The compiled flat gather emits exactly the all-gather the
        cost model prices — zero byte drift."""
        p = comm.size
        leaf = self._leaf(comm)
        axis = comm.axis_name

        def kernel(c):
            # [None]: the custom-vjp output defeats shard_map's
            # replication tracking, so stack instead of out_specs P()
            return F.fsdp_gather(c, leaf, comm)[None]

        fn = jax.jit(
            jax.shard_map(
                kernel, mesh=comm.mesh, in_specs=P(axis), out_specs=P(axis)
            )
        )
        rows = jnp.ones((p, leaf.chunk), jnp.float32)
        audit = hlo.audit_computation(fn, rows)
        predicted = costs.fsdp_gather_cost(leaf.chunk, 4, 1, p, "off")
        report = hlo.compare(audit, predicted)
        assert report.ok, report.summary()
        assert report.emitted_bytes == predicted.bytes

    def test_backward_scatter_bytes_match_cost(self, comm):
        """The gather's vjp reduce-scatters the cotangent; its audited
        wire bytes equal fsdp_scatter_cost exactly."""
        p = comm.size
        leaf = self._leaf(comm)
        axis = comm.axis_name

        def kernel(c):
            _, vjp = jax.vjp(lambda cc: F.fsdp_gather(cc, leaf, comm), c)
            (ct,) = vjp(jnp.ones(leaf.shape, jnp.float32))
            return ct

        fn = jax.jit(
            jax.shard_map(
                kernel, mesh=comm.mesh, in_specs=P(axis), out_specs=P(axis)
            )
        )
        rows = jnp.ones((p, leaf.chunk), jnp.float32)
        audit = hlo.audit_computation(fn, rows)
        rs = [c for c in audit.collectives if c.op == "reduce-scatter"]
        predicted = costs.fsdp_scatter_cost(p * leaf.chunk, 4, 1, p, "off")
        assert rs and sum(c.wire_bytes for c in rs) == predicted.bytes


class TestCheckpoint:
    def test_logical_roundtrip_bitwise(self, comm, monkeypatch, tmp_path):
        on = _make(monkeypatch, enabled=True)
        x, y = _data()
        logical = _init_logical(on)
        params = on.shard_params(logical)
        state = on.init_opt_state(params)
        step = on.make_train_step(_loss)
        params, state, _ = step(params, state, *on.shard_batch(x, y))
        path = on.save_checkpoint(str(tmp_path / "ck"), params, state)

        fresh = _make(monkeypatch, enabled=True)
        p2, s2 = fresh.load_checkpoint(path, logical)
        for a, b in zip(_leaves(params), _leaves(p2)):
            assert np.array_equal(a, b)
        for a, b in zip(_leaves(state), _leaves(s2)):
            assert np.array_equal(a, b)
        # and the restored state trains on, bit-compatibly
        fresh.make_train_step(_loss)(p2, s2, *fresh.shard_batch(x, y))

    def test_extra_records_algo_and_rules(self, comm, monkeypatch, tmp_path):
        on = _make(monkeypatch, enabled=True)
        params = on.shard_params(_init_logical(on))
        state = on.init_opt_state(params)
        path = on.save_checkpoint(str(tmp_path / "ck"), params, state)
        man = json.loads(
            (tmp_path / "ck" / "manifest.json").read_text()
        )
        extra = man["extra"]
        assert extra["algo"] == "fsdp" and extra["enabled"] is True
        assert F.PartitionRules.parse(extra["rules"]) == on.rules

    def test_wrong_algo_rejected(self, comm, monkeypatch, tmp_path):
        on = _make(monkeypatch, enabled=True)
        logical = _init_logical(on)
        resilience.save_checkpoint(
            {
                "params": jax.tree_util.tree_map(np.asarray, logical),
                "opt_state": jax.tree_util.tree_map(
                    np.asarray, OPT.init(logical)
                ),
            },
            str(tmp_path / "zk"), extra={"algo": "zero"},
        )
        with pytest.raises(resilience.CheckpointError, match="not fsdp"):
            on.load_checkpoint(str(tmp_path / "zk"), logical)


class TestShardedCheckpointKind:
    def test_jax_sharded_blobs_roundtrip(self, comm, tmp_path):
        """A mesh-sharded jax.Array checkpoints shard-by-shard (no host
        gather at save) under the ``jax_sharded`` record kind and
        reassembles bit-exactly."""
        p = comm.size
        full = np.arange(p * 5, dtype=np.float32).reshape(p, 5)
        arr = jax.device_put(jnp.asarray(full), comm.sharding(0, 2))
        path = resilience.save_checkpoint(
            {"w": arr, "s": np.float32(3.0)}, str(tmp_path / "ck")
        )
        man = json.loads((tmp_path / "ck" / "manifest.json").read_text())
        kinds = {r["kind"] for r in man["leaves"]}
        assert "jax_sharded" in kinds
        back = resilience.load_checkpoint(
            path, like={"w": full, "s": np.float32(0.0)}
        )
        assert np.array_equal(np.asarray(back["w"]), full)


class TestZeroComposition:
    def test_init_from_shards_matches_init(self, comm):
        zero = ZeroOptimizer(optax.adam(1e-2), comm, precision="off")
        params = {"w": jnp.arange(comm.size * 4, dtype=jnp.float32)}
        s1 = zero.init(params)
        flat = F.flat_shard_pytree(params, comm, "off", None)
        s2 = zero.init_from_shards(flat)
        for a, b in zip(_leaves(s1), _leaves(s2)):
            assert np.array_equal(a, b)

    def test_shard_update_is_public(self, comm):
        assert ZeroOptimizer.shard_update is ZeroOptimizer._shard_update
