"""Eager fusion engine oracles (ISSUE 4, core/fusion.py).

The contract under test: with fusion on (the default), an N-op elementwise
chain defers into one FusedExpr DAG and materializes as exactly ONE cached
XLA program at the first non-elementwise boundary — compiling once on first
use and never again (CompileWatcher oracle); results are numpy-exact across
every split, padded tails and mixed scalar operands; ``HEAT_TPU_FUSION=0``
restores pure-eager dispatch bit for bit; ``out=`` destinations never serve
stale deferred values; depth caps window unbounded chains.
"""

import os

import numpy as np
import pytest

import jax

import heat_tpu as ht
from heat_tpu import telemetry as tm
from heat_tpu.core import fusion
from heat_tpu.core import program_cache as pc


def _chain(a, b):
    """A 5-op elementwise pipeline: exp → sub → mul → clip → add."""
    return ht.clip(ht.exp(a) - b * 2.0, -1.0, 50.0) + 0.5


def _chain_np(an, bn):
    return np.clip(np.exp(an) - bn * 2.0, -1.0, 50.0) + 0.5


def _fusion_site():
    return dict(pc.stats()["sites"].get("fusion", {"hits": 0, "misses": 0}))


class TestOneProgram:
    """The dispatch oracle: a >=4-op chain is ONE program, compiled once."""

    def test_chain_is_one_cached_program(self):
        rng = np.random.default_rng(0)
        an = rng.standard_normal(13)
        bn = rng.standard_normal(13)
        a0, b0 = ht.array(an, split=0), ht.array(bn, split=0)
        a1, b1 = ht.array(an, split=0), ht.array(bn, split=0)

        before_site = _fusion_site()
        before = fusion.stats()
        r = _chain(a0, b0)
        assert r._fused_node() is not None, "chain did not defer"
        got = r.numpy()  # flush boundary
        after = fusion.stats()
        site = _fusion_site()
        assert after["deferred"] - before["deferred"] >= 4
        assert after["flushes"] - before["flushes"] == 1
        assert after["fallbacks"] == before["fallbacks"]
        # exactly ONE program entered the registry for the whole chain
        assert site["misses"] - before_site["misses"] == 1
        np.testing.assert_allclose(got, _chain_np(an, bn), rtol=1e-6)

        # second, identical chain: zero XLA compiles (deferral still runs
        # eval_shape, which is a jaxpr trace, not a compile), registry hit,
        # no new fused program
        hits0 = pc.stats()["hits"]
        misses0 = _fusion_site()["misses"]
        with tm.CompileWatcher() as w:
            got2 = _chain(a1, b1).numpy()
        assert w.backend_seconds == 0.0, (
            f"repeat chain recompiled: {dict(w.stages)}"
        )
        assert w.stages.get("backend_compile_duration", 0.0) == 0.0
        assert pc.stats()["hits"] > hits0
        assert _fusion_site()["misses"] == misses0
        np.testing.assert_array_equal(got, got2)

    def test_scalar_values_share_one_program(self):
        an = np.arange(11.0)
        a = ht.array(an, split=0)
        (a * 2.0).numpy()
        site0 = _fusion_site()
        np.testing.assert_array_equal((a * 3.0).numpy(), an * 3.0)
        site1 = _fusion_site()
        assert site1["misses"] == site0["misses"], (
            "x*2 and x*3 must share one executable (scalar is a runtime arg)"
        )
        assert site1["hits"] == site0["hits"] + 1


class TestNumpyParity:
    """Numpy-oracle equality across every split, padded tails included."""

    @pytest.mark.parametrize("split", [None, 0, 1])
    def test_chain_all_splits_padded_tail(self, split):
        rng = np.random.default_rng(42)
        an = rng.standard_normal((7, 5))  # 8-device mesh: both axes pad
        bn = rng.standard_normal((7, 5))
        a = ht.array(an, split=split)
        b = ht.array(bn, split=split)
        np.testing.assert_allclose(
            _chain(a, b).numpy(), _chain_np(an, bn), rtol=1e-6, atol=1e-9
        )

    @pytest.mark.parametrize("split", [None, 0])
    def test_mixed_replicated_and_split_operands(self, split):
        rng = np.random.default_rng(3)
        an = rng.standard_normal(11)
        bn = rng.standard_normal(11)
        a = ht.array(an, split=split)
        b = ht.array(bn)  # replicated, full logical extent -> pad node
        got = (ht.sqrt(ht.abs(a)) * b - 1).numpy()
        np.testing.assert_allclose(got, np.sqrt(np.abs(an)) * bn - 1, rtol=1e-6)

    def test_mixed_scalar_kinds(self):
        an = np.arange(9.0)
        a = ht.array(an, split=0)
        got = ((a + 2) * 0.5 - np.float32(1.25)).numpy()
        np.testing.assert_allclose(
            got, (an + 2) * 0.5 - np.float32(1.25), rtol=1e-7
        )

    def test_int_scalars_fold_bitwise_like_eager(self, monkeypatch):
        """Integer scalars are static constants: x**3 must lower to the
        same repeated-multiplication XLA folds for eager dispatch, not
        generic pow — bitwise-identical results."""
        rng = np.random.default_rng(11)
        an = (np.abs(rng.standard_normal(10007)) + 0.5).astype(np.float32)
        fused = ((ht.array(an, split=0) ** 3) * 1.0).numpy()
        monkeypatch.setenv("HEAT_TPU_FUSION", "0")
        eager = ((ht.array(an, split=0) ** 3) * 1.0).numpy()
        np.testing.assert_array_equal(fused, eager)

    def test_negative_zero_scalars_not_merged(self):
        """Scalar dedup must not merge 0.0 with -0.0 (python == equality
        would): copysign against -0.0 flips every sign."""
        an = np.arange(1.0, 6.0)
        a = ht.array(an, split=0)
        # ONE chain containing both +0.0 and -0.0 scalar operands
        r = ht.copysign(a + 0.0, -0.0)
        np.testing.assert_array_equal(r.numpy(), np.copysign(an + 0.0, -0.0))
        np.testing.assert_array_equal(
            np.signbit(r.numpy()), np.ones(5, dtype=bool)
        )

    def test_int_bool_chains(self):
        an = np.arange(-5, 8)
        a = ht.array(an, split=0)
        np.testing.assert_array_equal(
            ((a % 3 == 0) & (a > 0)).numpy(), ((an % 3 == 0) & (an > 0))
        )

    def test_reduction_is_a_flush_boundary(self):
        rng = np.random.default_rng(7)
        an = rng.standard_normal((6, 4))
        a = ht.array(an, split=0)
        r = ht.exp(a) * 2
        if fusion.active():  # class also runs under HEAT_TPU_FUSION=0 in CI
            assert r._fused_node() is not None
        np.testing.assert_allclose(
            ht.sum(r, axis=0).numpy(), (np.exp(an) * 2).sum(axis=0),
            rtol=1e-6,
        )

    def test_snapshot_semantics_on_inplace_mutation(self):
        """A chain captures operand buffers by value (eager parity): a
        later in-place write to the source must not change the chain."""
        an = np.arange(5.0)
        a = ht.array(an, split=0)
        r = a * 10
        a.lloc[0] = 99.0
        np.testing.assert_array_equal(r.numpy(), an * 10)

    def test_shared_subchain_computes_once(self):
        if not fusion.active():
            pytest.skip("flush-count oracle needs fusion on")
        an = np.arange(6.0) + 1
        a = ht.array(an, split=0)
        t = ht.log(a)  # shared sub-DAG
        u = t + 1
        v = t * 2
        before = fusion.stats()["flushes"]
        np.testing.assert_allclose(u.numpy(), np.log(an) + 1, rtol=1e-6)
        np.testing.assert_allclose(v.numpy(), np.log(an) * 2, rtol=1e-6)
        # one program per consumer; t is an interior shared node, so log
        # re-traces inside each program (documented FusedNode semantics)
        # rather than forcing an extra flush of t itself
        assert fusion.stats()["flushes"] - before == 2


class TestDepthCap:
    def test_depth_cap_flushes_in_windows(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_FUSION_DEPTH", "4")
        an = np.arange(10.0)
        a = ht.array(an, split=0)
        before = fusion.stats()["flushes"]
        r = a
        for _ in range(9):
            r = r + 1.0
        got = r.numpy()
        flushed = fusion.stats()["flushes"] - before
        assert flushed >= 2, "a 9-op chain under depth cap 4 must window-flush"
        np.testing.assert_array_equal(got, an + 9.0)

    def test_default_cap_read_from_env(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_FUSION_DEPTH", "7")
        assert fusion.depth_cap() == 7
        assert fusion.node_cap() == 28
        monkeypatch.delenv("HEAT_TPU_FUSION_DEPTH")
        assert fusion.depth_cap() == fusion.DEFAULT_DEPTH


class TestFusionOff:
    """HEAT_TPU_FUSION=0 restores pure-eager dispatch, bit for bit."""

    def test_env_zero_is_eager_and_bit_identical(self, monkeypatch):
        rng = np.random.default_rng(5)
        an = rng.standard_normal((7, 3))
        bn = rng.standard_normal((7, 3))
        for split in (None, 0, 1):
            a, b = ht.array(an, split=split), ht.array(bn, split=split)
            fused = _chain(a, b).numpy()
            monkeypatch.setenv("HEAT_TPU_FUSION", "0")
            before = fusion.stats()["deferred"]
            a2, b2 = ht.array(an, split=split), ht.array(bn, split=split)
            r = _chain(a2, b2)
            assert r._fused_node() is None, "fusion off must not defer"
            assert fusion.stats()["deferred"] == before
            eager = r.numpy()
            monkeypatch.delenv("HEAT_TPU_FUSION")
            np.testing.assert_array_equal(fused, eager)

    def test_fusing_context_overrides_env(self, monkeypatch):
        an = np.arange(4.0)
        monkeypatch.setenv("HEAT_TPU_FUSION", "0")
        a = ht.array(an, split=0)
        with ht.fusing():
            r = a + 1
            assert r._fused_node() is not None
        np.testing.assert_array_equal(r.numpy(), an + 1)
        monkeypatch.delenv("HEAT_TPU_FUSION")
        with ht.fusing(False):
            r2 = a + 1
            assert r2._fused_node() is None

    def test_fuse_decorator_flushes_at_return(self):
        an = np.arange(6.0)

        @ht.fuse
        def step(x):
            return ht.exp(x) * 0.5 - 1

        out = step(ht.array(an, split=0))
        assert out._fused_node() is None, "@ht.fuse must flush on return"
        np.testing.assert_allclose(out.numpy(), np.exp(an) * 0.5 - 1, rtol=1e-6)


class TestOutAliasing:
    """Satellite: an ``out=`` destination never observes stale deferred
    values, and chains referencing its old value stay correct."""

    def test_unshared_pending_out_target_discards_without_flush(self):
        """Overwriting an out= destination whose pending chain nothing
        else references must NOT compile-and-run the dead chain."""
        an, bn = np.arange(6.0), np.arange(6.0) * 3
        z = ht.array(an, split=0) * 3.0  # deferred, unshared
        before = fusion.stats()["flushes"]
        ht.add(ht.array(an, split=0), ht.array(bn, split=0), out=z)
        got = z.numpy()
        # the out= path computes eagerly and the dead `an*3` chain is
        # discarded, so NO fused program ran for this write
        assert fusion.stats()["flushes"] == before
        np.testing.assert_array_equal(got, an + bn)

    def test_out_target_pending_chain_is_flushed_before_write(self):
        an, bn = np.arange(5.0), np.arange(5.0) * 2
        xn, yn = np.ones(5), np.full(5, 3.0)
        a, b = ht.array(an, split=0), ht.array(bn, split=0)
        c = a + b  # deferred chain pending on c
        assert c._fused_node() is not None
        d = c * 2  # references c's node
        ht.add(ht.array(xn, split=0), ht.array(yn, split=0), out=c)
        np.testing.assert_array_equal(c.numpy(), xn + yn)
        # d captured c's OLD chain by node, not by destination
        np.testing.assert_array_equal(d.numpy(), (an + bn) * 2)

    def test_out_equal_to_operand(self):
        an = np.arange(7.0)
        a = ht.array(an, split=0)
        c = a * 3  # deferred
        ht.add(c, c, out=c)
        np.testing.assert_array_equal(c.numpy(), an * 6)


class TestFallbacks:
    def test_lambda_ops_fall_back_eager(self):
        import jax.numpy as jnp

        from heat_tpu.core import _operations

        before = fusion.stats()["fallbacks"]
        an = np.arange(5.0) + 0.25
        # an unregistered lambda must refuse deferral and dispatch eagerly
        # (modf used to be the in-tree example until ISSUE 7 converted it
        # to registered fusable helpers)
        r = _operations.local_op(lambda a: jnp.sin(a), ht.array(an, split=0))
        assert fusion.stats()["fallbacks"] > before
        assert r._fused_node() is None
        np.testing.assert_allclose(r.numpy(), np.sin(an))

    def test_modf_fuses(self):
        """PR 7 satellite: modf's parts are registered fusable ops — no
        fallback, and both parts defer."""
        before = fusion.stats()["fallbacks"]
        an = np.arange(5.0) + 0.25
        frac, intg = ht.modf(ht.array(an, split=0))
        assert fusion.stats()["fallbacks"] == before
        if fusion.active():
            assert frac._fused_node() is not None
        np.testing.assert_allclose(frac.numpy(), np.modf(an)[0])
        np.testing.assert_allclose(intg.numpy(), np.modf(an)[1])

    def test_kwarg_ops_fuse(self):
        an = np.linspace(-2, 2, 9)
        a = ht.array(an, split=0)
        before = fusion.stats()["deferred"]
        got = ht.round(ht.clip(a, -1.0, 1.0), decimals=1)
        assert got._fused_node() is not None
        assert fusion.stats()["deferred"] - before == 2
        np.testing.assert_allclose(
            got.numpy(), np.round(np.clip(an, -1.0, 1.0), 1)
        )

    def test_isclose_fuses(self):
        an = np.arange(6.0)
        a, b = ht.array(an, split=0), ht.array(an + 1e-9, split=0)
        r = ht.isclose(a, b)
        assert r._fused_node() is not None
        np.testing.assert_array_equal(r.numpy(), np.isclose(an, an + 1e-9))


class TestTelemetry:
    def test_counters_and_summarize_block(self):
        reg = tm.enable()
        reg.clear()
        try:
            an = np.arange(8.0)
            a = ht.array(an, split=0)
            (ht.exp(a) * 2 + 1).numpy()
            snap = reg.snapshot()["counters"]
            assert snap.get("fusion.deferred", 0) >= 3
            assert snap.get("fusion.flushes", 0) >= 1
            summary = tm.report.summarize()
            assert "fusion" in summary
            assert summary["fusion"]["flushes"] >= 1
            assert summary["fusion"]["nodes_per_flush"] > 0
            # one instant flush event feeds the Chrome trace
            assert any(e.get("kind") == "fusion" for e in reg.events)
        finally:
            tm.disable()
            reg.clear()


class TestMetadataWithoutFlush:
    def test_shape_queries_do_not_materialize(self):
        p = ht.get_comm().size
        a = ht.array(np.arange(11.0), split=0)
        r = a * 2 + 1
        assert r._fused_node() is not None
        assert r.shape == (11,)
        padded = -(-11 // p) * p  # ceil-rule tail pad for the active mesh
        assert r.padded_shape == (padded,)
        assert r.pad_count == padded - 11
        assert r.split == 0
        assert r._fused_node() is not None, "metadata reads must not flush"
        np.testing.assert_array_equal(r.numpy(), np.arange(11.0) * 2 + 1)


class TestDonationGuard:
    """A buffer captured by value into a pending chain must never be
    donated by a later in-place resplit_ (on donation-capable backends
    the chain's flush would read a deleted array)."""

    def test_captured_leaf_blocks_resplit_donation(self):
        an = np.arange(12.0).reshape(6, 2)
        a = ht.array(an, split=0)
        assert a._buffer_donatable()
        r = a * 2  # deferred chain captures a's buffer by value
        assert not a._buffer_donatable()
        a.resplit_(1)  # must relayout WITHOUT donating the old buffer
        assert a._buffer_donatable()  # fresh post-relayout buffer
        np.testing.assert_array_equal(r.numpy(), an * 2)
        np.testing.assert_array_equal(a.numpy(), an)

    def test_shared_chain_result_blocks_donation(self):
        an = np.arange(10.0)
        a = ht.array(an, split=0)
        r = a + 1          # deferred root
        d = r * 3          # consumes r's node -> r's flush is shared
        r.larray           # flush r; its buffer re-enters d's DAG as a leaf
        assert not r._buffer_donatable()
        r.resplit_(None)   # copies instead of donating
        np.testing.assert_array_equal(d.numpy(), (an + 1) * 3)

    def test_unshared_flush_keeps_donation(self):
        a = ht.array(np.arange(8.0), split=0)
        r = a + 1
        r.larray  # flushed, root never consumed by another DAG
        assert r._buffer_donatable()

    def test_resplit_of_still_deferred_shared_owner(self, monkeypatch):
        """resplit_ on an owner whose chain is still PENDING and shared
        must flush first and then skip donation — deciding donate before
        the flush would donate the buffer the sibling DAG references."""
        an = np.arange(12.0).reshape(6, 2)
        z = ht.array(an, split=0) + 1   # deferred
        w = z * 2                        # consumes z's pending node
        seen = {}
        orig = ht.DNDarray._relayout

        def spy(self, new_split, *, audit=False, donate=False):
            seen["donate"] = donate
            return orig(self, new_split, audit=audit, donate=donate)

        monkeypatch.setattr(ht.DNDarray, "_relayout", spy)
        z.resplit_(1)                    # flush happens inside, pre-decision
        assert seen["donate"] is False
        np.testing.assert_array_equal(w.numpy(), (an + 1) * 2)
        np.testing.assert_array_equal(z.numpy(), an + 1)

    def test_fallback_leaves_no_stale_capture_marks(self):
        """An op that falls back to eager dispatch must not leave its
        operands marked non-donatable."""
        import jax.numpy as jnp

        from heat_tpu.core import _operations

        an = np.arange(5.0) + 0.25
        a = ht.array(an, split=0)
        assert a._buffer_donatable()
        _operations.local_op(lambda v: jnp.cos(v), a)  # eager fallback
        assert a._buffer_donatable(), "fallback left a stale capture mark"

    def test_astype_copy_is_a_real_copy_same_dtype(self):
        """Same-dtype astype(copy=True) must not alias the source buffer
        (a donating resplit_ of either array would invalidate the other)."""
        a = ht.array(np.arange(6.0, dtype=np.float32).reshape(3, 2), split=0)
        b = a.astype(ht.float32)  # same dtype: jax cast is a no-op
        assert b.larray is not a.larray
        a.resplit_(1)  # donation-capable backends delete a's old buffer
        np.testing.assert_array_equal(
            b.numpy(), np.arange(6.0, dtype=np.float32).reshape(3, 2)
        )
