"""Tests for the Pallas flash-attention kernel.

On the CPU test mesh the kernel runs under the Pallas interpreter
(``interpret=True`` is the off-TPU default), so these exercise the exact
kernel program — grid, BlockSpecs, scratch carries — that compiles to
Mosaic on a real chip. Oracle: the dense numpy attention from
tests/test_parallel.py plus the XLA online-softmax path it must match.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heat_tpu.parallel import flash_attention, local_attention
from tests.test_parallel import dense_attention, make_qkv


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv(2, 96, 2, 16)
        out = flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            causal=causal, block_q=32, block_k=32,
        )
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_matches_local_attention_bitpattern(self):
        # same f32 online softmax as the XLA path — agreement should be tight
        q, k, v = make_qkv(1, 64, 2, 32, seed=3)
        a = flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            block_q=32, block_k=32,
        )
        b = local_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), block_size=32
        )
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)

    def test_ragged_seq_and_headdim(self):
        # T not a block multiple, D not lane-aligned — wrapper pads, output
        # sliced back; K tail padding must not leak into the softmax
        q, k, v = make_qkv(1, 50, 2, 24, seed=5)
        out = flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            block_q=32, block_k=32,
        )
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_kv_valid_masks_padding(self):
        q, k, v = make_qkv(1, 64, 2, 16, seed=7)
        valid = 40
        out = flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            kv_valid=valid, block_q=32, block_k=32,
        )
        ref = dense_attention(q, k, v, valid=valid)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_causal_first_row_defined(self):
        # causal row 0 attends only to k 0 — fully-masked guard must not NaN
        q, k, v = make_qkv(1, 32, 1, 16, seed=9)
        out = flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            causal=True, block_q=16, block_k=16,
        )
        assert np.isfinite(np.asarray(out)).all()

    def test_cross_attention_lengths(self):
        # Tq != Tk exercises independent q/k grids
        rng = np.random.default_rng(11)
        q = rng.standard_normal((2, 48, 2, 16)).astype(np.float32)
        k = rng.standard_normal((2, 80, 2, 16)).astype(np.float32)
        v = rng.standard_normal((2, 80, 2, 16)).astype(np.float32)
        out = flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            block_q=16, block_k=32,
        )
        s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(16)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bkhd->bqhd", p, v)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_grad_flows(self):
        # custom_vjp backward recomputes through the XLA path
        q, k, v = make_qkv(1, 32, 2, 16, seed=13)
        qj, kj, vj = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)

        def loss(q_, k_, v_):
            return flash_attention(q_, k_, v_, block_q=16, block_k=16).sum()

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(qj, kj, vj)

        def ref_loss(q_, k_, v_):
            return local_attention(q_, k_, v_, block_size=16).sum()

        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(qj, kj, vj)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), rtol=1e-4, atol=1e-5)

    def test_bf16_inputs(self):
        q, k, v = make_qkv(1, 64, 2, 16, seed=17)
        out = flash_attention(
            jnp.asarray(q, dtype=jnp.bfloat16),
            jnp.asarray(k, dtype=jnp.bfloat16),
            jnp.asarray(v, dtype=jnp.bfloat16),
            block_q=32, block_k=32,
        )
        assert out.dtype == jnp.bfloat16
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32), ref, rtol=0.05, atol=0.05
        )


class TestUlyssesPallas:
    def test_ulysses_pallas_matches_dense(self):
        import heat_tpu as ht

        comm = ht.get_comm()
        p = comm.size
        b, t, h, d = 2, 4 * p, p, 8
        q, k, v = make_qkv(b, t, h, d, seed=21)
        sharding = comm.sharding(1, 4)
        from heat_tpu.parallel import ulysses_attention

        out = ulysses_attention(
            jax.device_put(jnp.asarray(q), sharding),
            jax.device_put(jnp.asarray(k), sharding),
            jax.device_put(jnp.asarray(v), sharding),
            comm=comm, use_pallas=True,
        )
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


class TestPallasBackwardKernels:
    """Round-4 (VERDICT r3 item 5): the backward pass is two Pallas kernels
    (dq; dk/dv) from the saved O/log-sum-exp — oracle is autodiff through
    the XLA online-softmax path."""

    def _grads(self, fn, q, k, v, g):
        def loss(q_, k_, v_):
            return (fn(q_, k_, v_).astype(jnp.float32) * g.astype(jnp.float32)).sum()

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    @pytest.mark.parametrize(
        "b,t,h,d,causal,kv_valid",
        [
            (1, 256, 2, 64, False, None),
            (2, 384, 2, 32, True, None),
            (1, 300, 1, 64, False, 260),
            (1, 128, 2, 128, True, 100),
        ],
    )
    def test_f32_grads_match_xla_path(self, b, t, h, d, causal, kv_valid):
        from heat_tpu.parallel import flash_attention
        from heat_tpu.parallel.attention import local_attention

        rng = np.random.default_rng(t + d)
        q, k, v, g = (
            jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
            for _ in range(4)
        )
        gf = self._grads(
            lambda q_, k_, v_: flash_attention(
                q_, k_, v_, causal=causal, kv_valid=kv_valid, interpret=True
            ),
            q, k, v, g,
        )
        gr = self._grads(
            lambda q_, k_, v_: local_attention(
                q_, k_, v_, causal=causal, kv_valid=kv_valid
            ),
            q, k, v, g,
        )
        for name, a, bb in zip("qkv", gf, gr):
            err = float(jnp.abs(a - bb).max())
            ref = max(float(jnp.abs(bb).max()), 1.0)
            assert err < 2e-3 * ref, (name, err, ref)

    def test_bf16_grads_close(self):
        from heat_tpu.parallel import flash_attention
        from heat_tpu.parallel.attention import local_attention

        rng = np.random.default_rng(5)
        q, k, v, g = (
            jnp.asarray(rng.standard_normal((2, 256, 4, 64)), jnp.bfloat16)
            for _ in range(4)
        )
        gf = self._grads(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=True, interpret=True),
            q, k, v, g,
        )
        gr = self._grads(
            lambda q_, k_, v_: local_attention(q_, k_, v_, causal=True),
            q, k, v, g,
        )
        for name, a, bb in zip("qkv", gf, gr):
            af, bf = a.astype(jnp.float32), bb.astype(jnp.float32)
            rel = float(jnp.abs(af - bf).max()) / max(float(jnp.abs(bf).max()), 1.0)
            assert rel < 0.1, (name, rel)


class TestFusedBackward:
    """The fused single-pass backward must produce the SAME grads as the
    two-pass kernels (shared `_rebuild_probs`; only the accumulation
    schedule differs — f32 dQ resident vs per-pass scratch)."""

    def _grads(self, fn, q, k, v, g):
        def loss(q_, k_, v_):
            return (fn(q_, k_, v_).astype(jnp.float32) * g.astype(jnp.float32)).sum()

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    @pytest.mark.parametrize(
        "b,t,h,d,causal,kv_valid",
        [
            (1, 256, 2, 64, False, None),
            (2, 384, 2, 32, True, None),   # ragged t -> q/k pad rows
            (1, 256, 2, 64, True, 200),    # kv padding mask
        ],
    )
    def test_fused_matches_two_pass_f32(self, b, t, h, d, causal, kv_valid):
        from heat_tpu.parallel import flash_attention

        rng = np.random.default_rng(17)
        q, k, v, g = (
            jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
            for _ in range(4)
        )
        kw = dict(causal=causal, kv_valid=kv_valid, interpret=True,
                  block_q=128, block_k=128)
        g2 = self._grads(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, bwd_impl="two_pass", **kw),
            q, k, v, g,
        )
        gf = self._grads(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, bwd_impl="fused", **kw),
            q, k, v, g,
        )
        for name, a, bb in zip("qkv", gf, g2):
            err = float(jnp.abs(a - bb).max())
            ref = max(float(jnp.abs(bb).max()), 1.0)
            # identical math modulo f32 summation order
            assert err < 1e-5 * ref, (name, err, ref)

    def test_auto_resolves_and_matches(self):
        from heat_tpu.parallel import flash_attention
        from heat_tpu.parallel.pallas_attention import (
            _flash_bwd_fused,
            _fused_bwd_fits,
        )
        import heat_tpu.parallel.pallas_attention as pa

        # "auto" must actually take the fused branch at this shape (the
        # grads comparison alone would pass even if dispatch regressed to
        # two_pass — record the fused driver running)
        assert _fused_bwd_fits(256, 128)
        calls = []
        orig = _flash_bwd_fused

        def spy(*a, **kw):
            calls.append(1)
            return orig(*a, **kw)

        pa._flash_bwd_fused = spy
        rng = np.random.default_rng(23)
        q, k, v, g = (
            jnp.asarray(rng.standard_normal((1, 256, 2, 64)), jnp.bfloat16)
            for _ in range(4)
        )
        kw = dict(causal=True, interpret=True, block_q=128, block_k=128)
        g2 = self._grads(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, bwd_impl="two_pass", **kw),
            q, k, v, g,
        )
        ga = self._grads(
            lambda q_, k_, v_: flash_attention(q_, k_, v_, bwd_impl="auto", **kw),
            q, k, v, g,
        )
        pa._flash_bwd_fused = orig
        assert calls, "auto did not dispatch to the fused backward"
        for name, a, bb in zip("qkv", ga, g2):
            af, bf = a.astype(jnp.float32), bb.astype(jnp.float32)
            rel = float(jnp.abs(af - bf).max()) / max(float(jnp.abs(bf).max()), 1.0)
            # bf16 cast points differ only in dQ's final rounding
            assert rel < 2e-2, (name, rel)

    def test_bad_impl_raises(self):
        from heat_tpu.parallel import flash_attention

        q = jnp.zeros((1, 8, 1, 8), jnp.float32)
        with pytest.raises(ValueError, match="bwd_impl"):
            flash_attention(q, q, q, bwd_impl="nope", interpret=True)
