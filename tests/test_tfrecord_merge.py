"""imagenet TFRecord→HDF5 merge (reference heat/utils/data/_utils.py:47-226)
— TF-free re-design tested against a hand-encoded TFRecord."""

import base64
import io
import os
import struct

import numpy as np
import pytest

h5py = pytest.importorskip("h5py")
PIL = pytest.importorskip("PIL")
from PIL import Image

from heat_tpu.utils.data._utils import (
    _parse_example,
    dali_tfrecord2idx,
    merge_files_imagenet_tfrecord,
)


# -- hand protobuf encoder (test-side oracle) ---------------------------------

def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _ld(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _bytes_feature(vals) -> bytes:
    inner = b"".join(_ld(1, v) for v in vals)
    return _ld(1, inner)  # Feature.bytes_list


def _int64_feature(vals) -> bytes:
    inner = b"".join(_varint(1 << 3) + _varint(v) for v in vals)
    return _ld(3, inner)  # Feature.int64_list


def _float_feature(vals) -> bytes:
    packed = b"".join(struct.pack("<f", v) for v in vals)
    inner = _ld(1, packed)  # packed floats
    return _ld(2, inner)  # Feature.float_list


def _example(features: dict) -> bytes:
    entries = b""
    for k, feat in features.items():
        entry = _ld(1, k.encode()) + _ld(2, feat)
        entries += _ld(1, entry)  # Features.feature map entry
    return _ld(1, entries)  # Example.features


def _write_tfrecord(path, payloads):
    with open(path, "wb") as f:
        for p in payloads:
            f.write(struct.pack("<Q", len(p)))
            f.write(b"\0\0\0\0")  # length crc (unchecked)
            f.write(p)
            f.write(b"\0\0\0\0")  # payload crc


def _jpeg_bytes(arr) -> bytes:
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")  # lossless, PIL-decodable
    return buf.getvalue()


def _make_example(rng, h, w, label, name):
    img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
    return img, _example(
        {
            "image/encoded": _bytes_feature([_jpeg_bytes(img)]),
            "image/height": _int64_feature([h]),
            "image/width": _int64_feature([w]),
            "image/channels": _int64_feature([3]),
            "image/class/label": _int64_feature([label]),
            "image/object/bbox/xmin": _float_feature([0.1]),
            "image/object/bbox/xmax": _float_feature([0.9]),
            "image/object/bbox/ymin": _float_feature([0.2]),
            "image/object/bbox/ymax": _float_feature([0.8]),
            "image/object/bbox/label": _int64_feature([label]),
            "image/format": _bytes_feature([b"PNG"]),
            "image/filename": _bytes_feature([name.encode()]),
            "image/class/synset": _bytes_feature([b"n0000001"]),
            "image/class/text": _bytes_feature([b"thing"]),
        }
    )


class TestParseExample:
    def test_roundtrip_fields(self):
        rng = np.random.default_rng(0)
        img, payload = _make_example(rng, 8, 6, 7, "a.png")
        feats = _parse_example(payload)
        assert int(feats["image/class/label"][0]) == 7
        assert int(feats["image/height"][0]) == 8
        assert abs(feats["image/object/bbox/xmin"][0] - 0.1) < 1e-6
        assert feats["image/format"][0] == b"PNG"
        arr = np.asarray(Image.open(io.BytesIO(feats["image/encoded"][0])))
        np.testing.assert_array_equal(arr, img)


class TestMerge:
    def test_merge_train_and_val(self, tmp_path):
        rng = np.random.default_rng(1)
        imgs = []
        train_payloads, val_payloads = [], []
        for i in range(3):
            img, p = _make_example(rng, 8, 6, i + 1, f"t{i}.png")
            imgs.append(img)
            train_payloads.append(p)
        vimg, vp = _make_example(rng, 5, 4, 9, "v0.png")
        val_payloads.append(vp)
        _write_tfrecord(tmp_path / "train-00000", train_payloads)
        _write_tfrecord(tmp_path / "val-00000", val_payloads)

        merge_files_imagenet_tfrecord(str(tmp_path), str(tmp_path))

        with h5py.File(tmp_path / "imagenet_merged.h5") as f:
            assert f["images"].shape == (3,)
            assert f["metadata"].shape == (3, 9)
            assert f["file_info"].shape == (3, 4)
            # labels shifted to 0-based (reference :186)
            np.testing.assert_allclose(f["metadata"][:, 3], [0, 1, 2])
            # decode an image back per the documented recipe
            raw = base64.binascii.a2b_base64(f["images"][0])
            h, w = int(f["metadata"][0, 0]), int(f["metadata"][0, 1])
            np.testing.assert_array_equal(
                np.frombuffer(raw, np.uint8).reshape(h, w, 3), imgs[0]
            )
            assert f["file_info"][0, 0] == b"PNG"
        with h5py.File(tmp_path / "imagenet_merged_validation.h5") as f:
            assert f["images"].shape == (1,)
            assert f["metadata"][0, 3] == 8.0  # label 9 -> 0-based 8

    def test_merge_without_bbox_uses_sentinel(self, tmp_path):
        rng = np.random.default_rng(2)
        img = rng.integers(0, 255, (4, 4, 3), dtype=np.uint8)
        payload = _example(
            {
                "image/encoded": _bytes_feature([_jpeg_bytes(img)]),
                "image/class/label": _int64_feature([5]),
            }
        )
        _write_tfrecord(tmp_path / "train-0", [payload])
        merge_files_imagenet_tfrecord(str(tmp_path), str(tmp_path))
        with h5py.File(tmp_path / "imagenet_merged.h5") as f:
            np.testing.assert_allclose(
                f["metadata"][0], [4, 4, 3, 4, 0.0, 4.0, 0.0, 4.0, -2.0]
            )


class TestDaliIndex:
    def test_index_offsets(self, tmp_path):
        rng = np.random.default_rng(3)
        _, p1 = _make_example(rng, 4, 4, 1, "x.png")
        _, p2 = _make_example(rng, 4, 4, 2, "y.png")
        (tmp_path / "train").mkdir()
        (tmp_path / "train_idx").mkdir()
        (tmp_path / "val").mkdir()
        (tmp_path / "val_idx").mkdir()
        _write_tfrecord(tmp_path / "train" / "t-0", [p1, p2])
        dali_tfrecord2idx(
            str(tmp_path / "train"), str(tmp_path / "train_idx"),
            str(tmp_path / "val"), str(tmp_path / "val_idx"),
        )
        lines = (tmp_path / "train_idx" / "t-0.idx").read_text().splitlines()
        assert len(lines) == 2
        off0, len0 = map(int, lines[0].split())
        off1, _ = map(int, lines[1].split())
        assert off0 == 0 and off1 == len0 == 16 + len(p1)


class TestTruncation:
    def test_truncated_frame_raises(self, tmp_path):
        rng = np.random.default_rng(5)
        _, p1 = _make_example(rng, 4, 4, 1, "t.png")
        path = tmp_path / "train-0"
        _write_tfrecord(path, [p1])
        data = path.read_bytes()
        path.write_bytes(data[:-10])  # chop the tail
        with pytest.raises(ValueError, match="truncated TFRecord"):
            merge_files_imagenet_tfrecord(str(tmp_path), str(tmp_path))
