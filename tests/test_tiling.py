"""Tests for heat_tpu.core.tiling (reference: heat/core/tests/test_tiling.py).

Oracle: tile boundaries recomputed with numpy from the ceil chunk rule;
get/set round-trips against the gathered global array."""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core.tiling import SplitTiles, SquareDiagTiles


@pytest.fixture(scope="module")
def comm():
    return ht.get_comm()


class TestSplitTiles:
    def test_tile_dimensions_cover_shape(self, comm):
        n, m = 3 * comm.size + 1, 2 * comm.size
        a = ht.random.randn(n, m, split=0, comm=comm)
        tiles = SplitTiles(a)
        dims = tiles.tile_dimensions
        assert dims.shape == (2, comm.size)
        assert dims[0].sum() == n and dims[1].sum() == m
        assert (tiles.tile_ends_g[0] <= n).all()

    def test_getitem_matches_numpy(self, comm):
        n = 4 * comm.size
        a = ht.arange(n * n, split=0, comm=comm).reshape((n, n))
        ref = a.numpy()
        tiles = SplitTiles(a)
        c = -(-n // comm.size)
        for i in (0, comm.size - 1):
            got = np.asarray(tiles[i, 0])
            np.testing.assert_array_equal(
                got, ref[i * c : min((i + 1) * c, n), :c]
            )
        # slices merge adjacent tiles
        got = np.asarray(tiles[0:2, :])
        np.testing.assert_array_equal(got, ref[: 2 * c, :])

    def test_setitem_roundtrip(self, comm):
        n = 2 * comm.size
        a = ht.zeros((n, n), split=0, comm=comm)
        tiles = SplitTiles(a)
        block = np.full(tiles.get_tile_size((0, 0)), 7.0, dtype=np.float32)
        tiles[0, 0] = block
        ref = a.numpy()
        np.testing.assert_array_equal(ref[: block.shape[0], : block.shape[1]], block)
        assert ref.sum() == block.sum()

    def test_tile_locations(self, comm):
        a = ht.zeros((comm.size * 2, 4), split=0, comm=comm)
        locs = SplitTiles(a).tile_locations
        assert locs.shape == (comm.size, comm.size)
        # ownership varies along the split dim (axis 0)
        for r in range(comm.size):
            assert (locs[r] == r).all()
        b = ht.zeros((4, 4), comm=comm)  # replicated
        assert (SplitTiles(b).tile_locations == -1).all()

    def test_validation(self, comm):
        with pytest.raises(TypeError):
            SplitTiles(np.zeros((4, 4)))
        a = ht.zeros((4, 4), split=0, comm=comm)
        t = SplitTiles(a)
        with pytest.raises(IndexError):
            t[comm.size + 1, 0]
        with pytest.raises(ValueError):
            t[0, 0, 0]


class TestSquareDiagTiles:
    @pytest.mark.parametrize("split", [0, 1])
    @pytest.mark.parametrize("shape", [(16, 16), (24, 12), (12, 24)])
    def test_boundaries_cover_matrix(self, comm, split, shape):
        a = ht.random.randn(*shape, split=split, comm=comm)
        tiles = SquareDiagTiles(a, tiles_per_proc=2)
        m, n = shape
        rows = tiles.row_indices
        cols = tiles.col_indices
        assert rows[0] == 0 and cols[0] == 0
        assert sorted(rows) == rows and sorted(cols) == cols
        # reassembling all tiles reproduces the matrix
        ref = a.numpy()
        acc = np.zeros_like(ref)
        for i in range(tiles.tile_rows):
            for j in range(tiles.tile_columns):
                r0, r1, c0, c1 = tiles.get_start_stop((i, j))
                acc[r0:r1, c0:c1] = np.asarray(tiles[i, j])
        np.testing.assert_allclose(acc, ref, rtol=1e-6)

    def test_diag_tiles_square(self, comm):
        n = 8 * comm.size
        a = ht.random.randn(n, n, split=0, comm=comm)
        tiles = SquareDiagTiles(a, tiles_per_proc=2)
        for i in range(min(tiles.tile_rows, tiles.tile_columns)):
            r0, r1, c0, c1 = tiles.get_start_stop((i, i))
            assert r1 - r0 == c1 - c0  # diagonal tiles are square
            assert r0 == c0

    def test_per_process_counts(self, comm):
        n = 4 * comm.size
        a = ht.random.randn(n, n, split=0, comm=comm)
        tiles = SquareDiagTiles(a, tiles_per_proc=2)
        assert sum(tiles.tile_rows_per_process) == tiles.tile_rows
        assert tiles.last_diagonal_process == comm.size - 1
        tm = tiles.tile_map
        assert tm.shape == (tiles.tile_rows, tiles.tile_columns, 3)
        assert (tm[..., 2] < comm.size).all()

    def test_setitem(self, comm):
        n = 4 * comm.size
        a = ht.zeros((n, n), split=0, comm=comm)
        tiles = SquareDiagTiles(a, tiles_per_proc=1)
        r0, r1, c0, c1 = tiles.get_start_stop((1, 1))
        tiles[1, 1] = np.ones((r1 - r0, c1 - c0), dtype=np.float32)
        assert a.numpy().sum() == (r1 - r0) * (c1 - c0)

    def test_validation(self, comm):
        a = ht.zeros((4, 4, 4), split=0, comm=comm)
        with pytest.raises(ValueError):
            SquareDiagTiles(a)
        b = ht.zeros((4, 4), comm=comm)
        with pytest.raises(ValueError):
            SquareDiagTiles(b)  # replicated not allowed
        c = ht.zeros((4, 4), split=0, comm=comm)
        with pytest.raises(ValueError):
            SquareDiagTiles(c, tiles_per_proc=0)
