"""heat_tpu.telemetry — enable/disable semantics, JSONL event schema, span
nesting and async-correct timing, AOT compile-vs-execute split, and the
collective byte accounting validated against the analytic volumes
(telemetry/collectives.py; the redistribution arithmetic of
arXiv:2112.01075 §2). Runs on the conftest CPU mesh (8 devices by default,
swept by scripts/run_ci.sh — byte expectations are computed from the live
mesh size, not hard-coded)."""

import json
import math
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import telemetry as tm
from heat_tpu.core.communication import get_comm
from heat_tpu.telemetry import collectives as tcoll

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def telem(tmp_path):
    """Telemetry enabled with a fresh JSONL sink; always disabled + cleared
    afterwards so the rest of the suite runs on the no-op path."""
    sink = tmp_path / "events.jsonl"
    reg = tm.enable(str(sink))
    reg.clear()
    yield reg, sink
    tm.disable()
    reg.clear()


class TestEnableDisable:
    def test_disabled_by_default_and_noop(self):
        assert not tm.enabled()
        reg = tm.get_registry()
        before = len(reg.events)
        s = tm.span("nothing", bytes=123)
        with s as sp:
            sp.output(jnp.ones(2))
            sp.add_fields(extra=1)
        # the disabled span is one shared object — zero per-call allocation
        assert s is tm.span("something_else")
        assert len(reg.events) == before
        tm.trace_event("all_gather")
        assert len(reg.events) == before
        assert "traced.all_gather" not in reg.counters

    def test_enable_disable_cycle(self, tmp_path):
        reg = tm.enable(str(tmp_path / "s.jsonl"))
        try:
            assert tm.enabled()
            assert reg.sink_path == str(tmp_path / "s.jsonl")
        finally:
            tm.disable()
        assert not tm.enabled()
        assert reg.sink_path is None

    def test_disabled_resplit_emits_nothing(self):
        reg = tm.get_registry()
        reg.clear()
        x = ht.array(np.arange(32, dtype=np.float32).reshape(8, 4), split=0)
        x.resplit(1)
        assert [e for e in reg.events if e["kind"] == "span"] == []


class TestEventSchemaAndSink:
    def test_jsonl_schema(self, telem):
        reg, sink = telem
        with tm.span("alpha", bytes=10, collective="none"):
            pass
        tm.trace_event("psum", axis="proc")
        lines = [json.loads(l) for l in sink.read_text().splitlines() if l]
        assert len(lines) >= 2
        for ev in lines:
            assert isinstance(ev["ts"], float)
            assert isinstance(ev["kind"], str)
            assert isinstance(ev["name"], str)
        span_ev = next(e for e in lines if e["kind"] == "span")
        assert span_ev["name"] == "alpha"
        assert span_ev["bytes"] == 10
        assert span_ev["seconds"] >= 0
        assert {"depth", "parent"} <= set(span_ev)
        trace_ev = next(e for e in lines if e["kind"] == "collective_trace")
        assert trace_ev["name"] == "psum" and trace_ev["axis"] == "proc"
        # the sink and the in-memory stream carry identical records
        assert len(reg.events) == len(lines)

    def test_load_events_roundtrip(self, telem):
        reg, sink = telem
        with tm.span("one"):
            pass
        evs = tm.report.load_events(str(sink))
        assert [e["name"] for e in evs if e["kind"] == "span"] == ["one"]

    def test_counters_accumulate(self, telem):
        reg, _ = telem
        with tm.span("op", bytes=100):
            pass
        with tm.span("op", bytes=50):
            pass
        assert reg.counters["span.op.count"] == 2
        assert reg.counters["span.op.bytes"] == 150
        assert reg.counters["span.op.seconds"] > 0

    def test_clear_by_kind_keeps_other_records(self, telem):
        # the harness drops warmup spans this way — the compile and
        # collective-trace events (which only fire during warmup) and the
        # counters/watermarks must survive
        reg, _ = telem
        with tm.span("op", bytes=100):
            pass
        reg.emit("compile", "backend_compile", seconds=0.5)
        reg.high_water("live_bytes.total", 42)
        reg.clear(kinds=("span",))
        kinds = [e["kind"] for e in reg.events]
        assert "span" not in kinds
        assert "compile" in kinds
        assert reg.counters["span.op.count"] == 1
        assert reg.watermarks["live_bytes.total"] == 42
        reg.clear()
        assert not reg.events and not reg.counters and not reg.watermarks


class TestSpanNesting:
    def test_parent_and_depth(self, telem):
        reg, _ = telem
        with tm.span("outer"):
            with tm.span("inner"):
                pass
        spans = [e for e in reg.events if e["kind"] == "span"]
        inner, outer = spans  # inner exits (and is recorded) first
        assert inner["name"] == "inner"
        assert inner["parent"] == "outer" and inner["depth"] == 1
        assert outer["parent"] is None and outer["depth"] == 0

    def test_span_blocks_on_outputs(self, telem):
        reg, _ = telem
        x = jnp.ones((256, 256))
        f = jax.jit(lambda a: a @ a)
        f(x).block_until_ready()  # compile outside the span
        with tm.span("gemm") as sp:
            sp.output(f(x))
        ev = [e for e in reg.events if e["kind"] == "span"][-1]
        # the async dispatch returns in ~µs; a recorded duration at least
        # covers the host->ready wait (no strict lower bound on CPU, just
        # assert the span recorded a finite, nonnegative clock)
        assert ev["seconds"] >= 0

    def test_span_survives_exception(self, telem):
        reg, _ = telem
        with pytest.raises(ValueError):
            with tm.span("boom"):
                raise ValueError("x")
        errs = [e for e in reg.events if e["kind"] == "span_error"]
        assert len(errs) == 1 and errs[0]["name"] == "boom"
        # stack unwound: a follow-up span is top-level again
        with tm.span("after"):
            pass
        after = [e for e in reg.events if e["kind"] == "span"][-1]
        assert after["parent"] is None and after["depth"] == 0


class TestCompileSplit:
    def test_measure_compile_is_aot(self, telem):
        reg, _ = telem

        def f(x):
            return (x @ x.T).sum()

        x = jnp.ones((32, 32), jnp.float32)
        secs, compiled = tm.measure_compile(f, x)
        assert secs > 0
        # the AOT executable runs without recompiling
        out = compiled(x)
        np.testing.assert_allclose(np.asarray(out), 32.0 * 32 * 32)
        evs = [e for e in reg.events
               if e["kind"] == "compile" and e.get("mode") == "aot"]
        assert len(evs) == 1 and evs[0]["seconds"] == pytest.approx(secs)

    def test_compile_watcher_splits_compile_from_execute(self):
        # works with telemetry disabled — the harness uses it unconditionally
        @jax.jit
        def g(x):
            return jnp.tanh(x * 3.0).sum()

        x = jnp.ones((64,), jnp.float32)
        with tm.CompileWatcher() as first:
            g(x).block_until_ready()
        with tm.CompileWatcher() as second:
            g(x).block_until_ready()
        assert first.seconds > 0
        assert first.stages["backend_compile_duration"] > 0
        # cached second call: no backend compile attributed to it
        assert second.stages.get("backend_compile_duration", 0.0) == 0.0
        assert second.seconds < first.seconds


class TestCollectiveCostModel:
    def test_relayout_cases(self):
        b = 64 * 64 * 4
        assert tcoll.relayout_cost((64, 64), 4, 0, 0, 8).kind == "none"
        assert tcoll.relayout_cost((64, 64), 4, 0, 1, 1).kind == "none"
        c = tcoll.relayout_cost((64, 64), 4, None, 0, 8)
        assert c.kind == "local-slice" and c.bytes == 0
        c = tcoll.relayout_cost((64, 64), 4, 0, None, 8)
        assert c.kind == "all-gather" and c.bytes == b * 7
        c = tcoll.relayout_cost((64, 64), 4, 0, 1, 8)
        assert c.kind == "all-to-all" and c.bytes == b * 7 // 8
        assert c.as_fields() == {
            "collective": "all-to-all", "bytes": b * 7 // 8, "steps": 1
        }

    def test_kernel_costs(self):
        c = tcoll.tsqr_cost(64, 8, 4, 8)
        assert c.kind == "all-gather" and c.bytes == 8 * 7 * 8 * 8 * 4
        c = tcoll.ring_cdist_cost(16, 8, 4, 8)
        assert c.kind == "ppermute-ring" and c.steps == 8
        assert c.bytes == 8 * 8 * math.ceil(16 / 8) * 8 * 4
        c = tcoll.gram_ring_cost(64, 16, 4, 8)
        assert c.bytes > 0 and c.steps == 8
        for fn in (tcoll.tsqr_cost, tcoll.gram_ring_cost):
            assert fn(64, 8, 4, 1).kind == "none"
        assert tcoll.ring_cdist_cost(16, 8, 4, 1).kind == "none"


class TestByteAccounting:
    """Instrumented ops report the analytic wire volumes (computed from the
    live mesh size, so the run_ci.sh size sweep stays green)."""

    def test_resplit_all_to_all_volume(self, telem):
        reg, _ = telem
        p = get_comm().size
        xn = np.arange(64 * 64, dtype=np.float32).reshape(64, 64)
        x = ht.array(xn, split=0)
        reg.clear()
        y = x.resplit(1)
        np.testing.assert_allclose(y.numpy(), xn)
        spans = [e for e in reg.events
                 if e["kind"] == "span" and e["name"] == "resplit"]
        assert len(spans) == 1
        ev = spans[0]
        if p > 1:
            assert ev["collective"] == "all-to-all"
            assert ev["bytes"] == 64 * 64 * 4 * (p - 1) // p
        else:
            assert ev["collective"] == "none" and ev["bytes"] == 0
        assert ev["old_split"] == 0 and ev["new_split"] == 1
        # the inner relayout primitive nests under the op span
        inner = [e for e in reg.events
                 if e["kind"] == "span" and e["name"] == "relayout"]
        assert len(inner) == 1 and inner[0]["parent"] == "resplit"

    def test_ring_cdist_volume(self, telem):
        reg, _ = telem
        p = get_comm().size
        if p == 1:
            pytest.skip("ring kernel needs a >1-position mesh")
        rng = np.random.default_rng(0)
        xn = rng.standard_normal((16, 8)).astype(np.float32)
        yn = rng.standard_normal((12, 8)).astype(np.float32)
        x = ht.array(xn, split=0)
        y = ht.array(yn, split=0)
        reg.clear()
        d = ht.spatial.cdist(x, y, ring=True)
        ref = np.sqrt(((xn[:, None, :] - yn[None, :, :]) ** 2).sum(-1))
        np.testing.assert_allclose(d.numpy(), ref, atol=1e-4)
        spans = [e for e in reg.events
                 if e["kind"] == "span" and e["name"] == "ring_cdist"]
        assert len(spans) == 1
        ev = spans[0]
        # the default double-buffered schedule peels the final dead hop
        # (p-1 hops); HEAT_TPU_RING_OVERLAP=0 restores the p-hop serial
        # kernel (core/relayout_planner.ring_overlap)
        from heat_tpu.core import relayout_planner

        hops = p - 1 if relayout_planner.ring_overlap() else p
        assert ev["collective"] == "ppermute-ring" and ev["steps"] == hops
        assert ev["bytes"] == p * hops * math.ceil(12 / p) * 8 * 4

    def test_tsqr_volume(self, telem):
        reg, _ = telem
        p = get_comm().size
        if p == 1:
            pytest.skip("TSQR kernel needs a >1-position mesh")
        rng = np.random.default_rng(1)
        an = rng.standard_normal((64, 8)).astype(np.float32)
        a = ht.array(an, split=0)
        reg.clear()
        q, r = ht.linalg.qr(a)
        np.testing.assert_allclose((q @ r).numpy(), an, atol=1e-4)
        spans = [e for e in reg.events
                 if e["kind"] == "span" and e["name"] == "tsqr"]
        assert len(spans) == 1
        k1 = min(math.ceil(64 / p), 8)
        assert spans[0]["collective"] == "all-gather"
        assert spans[0]["bytes"] == p * (p - 1) * k1 * 8 * 4

    def test_traced_collective_events(self, telem):
        reg, _ = telem
        comm = get_comm()
        if comm.size == 1:
            pytest.skip("collective wrappers need a >1-position mesh")
        xn = np.arange(comm.padded_size(8), dtype=np.float32)
        xs = jax.device_put(xn, comm.sharding(0, 1))
        reg.clear()
        out = jax.shard_map(
            lambda v: comm.psum(jnp.sum(v)),
            mesh=comm.mesh,
            in_specs=comm.spec(0, 1),
            out_specs=jax.sharding.PartitionSpec(),
        )(xs)
        assert float(out) == pytest.approx(xn.sum())
        assert reg.counters.get("traced.psum", 0) >= 1
        names = [e["name"] for e in reg.events
                 if e["kind"] == "collective_trace"]
        assert "psum" in names


class TestMemoryWatermark:
    def test_watermark_snapshot_and_event(self, telem):
        reg, _ = telem
        keep = ht.array(np.ones((32, 32), dtype=np.float32), split=0)
        snap = tm.memory.watermark("unit")
        assert snap["total"] > 0 and snap["arrays"] > 0
        assert sum(snap["per_device"].values()) == snap["total"]
        evs = [e for e in reg.events if e["kind"] == "memory"]
        assert len(evs) == 1 and evs[0]["name"] == "unit"
        assert reg.watermarks["live_bytes.total"] >= snap["total"] or \
            reg.watermarks["live_bytes.total"] == snap["total"]
        del keep

    def test_probe_works_disabled(self):
        # plain probe: no event, but a usable snapshot
        reg = tm.get_registry()
        before = len(reg.events)
        snap = tm.memory.watermark("quiet")
        assert snap["total"] >= 0
        assert len(reg.events) == before


class TestReport:
    def test_summarize_shape(self):
        events = [
            {"kind": "span", "name": "resplit", "seconds": 0.5,
             "bytes": 100, "collective": "all-to-all"},
            {"kind": "span", "name": "resplit", "seconds": 0.25, "bytes": 50},
            {"kind": "span", "name": "tsqr", "seconds": 0.1, "bytes": 7},
            # nested primitive under an op span: same cost, same window —
            # must NOT become a second phase row (double-counting)
            {"kind": "span", "name": "relayout", "seconds": 0.5,
             "bytes": 100, "depth": 1, "parent": "resplit"},
            {"kind": "compile", "name": "backend_compile", "seconds": 0.125},
            {"kind": "compile", "name": "f", "seconds": 0.25, "mode": "aot"},
            {"kind": "collective_trace", "name": "psum"},
            {"kind": "collective_trace", "name": "psum"},
            {"kind": "memory", "name": "w", "total": 10},
        ]
        s = tm.report.summarize(events, watermarks={"live_bytes.total": 123})
        assert s["phases"]["resplit"] == {
            "calls": 2, "execute_seconds": 0.75, "bytes_moved": 150,
            "collective": "all-to-all",
        }
        assert s["phases"]["tsqr"]["bytes_moved"] == 7
        assert "relayout" not in s["phases"]
        assert s["compile_seconds"] == pytest.approx(0.375)
        assert s["compile_events"] == 2
        assert s["traced_collectives"] == {"psum": 2}
        assert s["peak_live_bytes"] == 123
        assert s["events"] == len(events)

    def test_bench_fields_gated(self, telem):
        with tm.span("op", bytes=5):
            pass
        fields = tm.report.bench_fields()
        assert "telemetry" in fields
        assert fields["telemetry"]["phases"]["op"]["bytes_moved"] == 5
        tm.disable()
        assert tm.report.bench_fields() == {}


class TestEnvActivation:
    def test_env_var_enables_and_streams_jsonl(self, tmp_path):
        """HEAT_TPU_TELEMETRY=1 turns recording on at import and streams
        span events (with analytic bytes) to HEAT_TPU_TELEMETRY_SINK."""
        sink = tmp_path / "ev.jsonl"
        code = (
            "import heat_tpu as ht, numpy as np\n"
            "assert ht.telemetry.enabled()\n"
            "x = ht.array(np.arange(64, dtype=np.float32).reshape(16, 4),"
            " split=0)\n"
            "y = x.resplit(1)\n"
            "print('DEVICES', ht.core.communication.get_comm().size)\n"
        )
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update({
            "HEAT_TPU_TELEMETRY": "1",
            "HEAT_TPU_TELEMETRY_SINK": str(sink),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        })
        r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-800:]
        evs = tm.report.load_events(str(sink))
        spans = [e for e in evs if e["kind"] == "span"
                 and e["name"] == "resplit"]
        assert len(spans) == 1
        assert spans[0]["bytes"] == 16 * 4 * 4 * 3 // 4  # all-to-all, p=4
        assert spans[0]["collective"] == "all-to-all"
