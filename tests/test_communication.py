"""Direct unit tests of MeshCommunication — chunk arithmetic, sharding
factories, and every collective wrapper under shard_map (VERDICT r2 item 1;
the reference dedicates 2,467 LoC to its MPI wrapper tests,
reference heat/core/tests/test_communication.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

import heat_tpu as ht
from heat_tpu.core.communication import (
    CommunicationError,
    MeshCommunication,
    get_comm,
    sanitize_comm,
    use_comm,
)


@pytest.fixture(scope="module")
def comm():
    return get_comm()


class TestChunkArithmetic:
    """The ceil-rule layout contract (reference communication.py:161-209
    uses n//p + remainder; ours is ceil(n/p) with short/empty tails — the
    physical XLA shard rule)."""

    def test_chunk_size_exact_division(self, comm):
        p = comm.size
        assert comm.chunk_size(4 * p) == 4

    def test_chunk_size_ceil(self, comm):
        p = comm.size
        assert comm.chunk_size(4 * p + 1) == 5

    def test_chunk_size_one(self, comm):
        assert comm.chunk_size(1) == 1

    def test_chunk_size_zero(self, comm):
        assert comm.chunk_size(0) == 0

    def test_padded_size_multiple(self, comm):
        p = comm.size
        for n in (1, p - 1 or 1, p, p + 1, 3 * p + 2):
            P = comm.padded_size(n)
            assert P % p == 0 and P >= n and P - n < p * comm.chunk_size(n)

    def test_padded_shape_none_split(self, comm):
        assert comm.padded_shape((5, 7), None) == (5, 7)

    def test_padded_shape_split0(self, comm):
        p = comm.size
        assert comm.padded_shape((p + 1, 3), 0) == (2 * p, 3)

    def test_padded_shape_split1(self, comm):
        p = comm.size
        got = comm.padded_shape((3, p + 1), 1)
        assert got == (3, 2 * p)

    def test_chunk_offsets_cover_range(self, comm):
        n = 3 * comm.size + 2
        covered = []
        for r in range(comm.size):
            off, lshape, sl = comm.chunk((n,), 0, r)
            assert sl[0] == slice(off, off + lshape[0])
            covered.extend(range(off, off + lshape[0]))
        assert covered == list(range(n))

    def test_chunk_tail_positions_empty(self, comm):
        if comm.size < 2:
            pytest.skip("needs >1 device")
        # n=1 over p devices: only position 0 owns data
        for r in range(1, comm.size):
            _, lshape, _ = comm.chunk((1,), 0, r)
            assert lshape[0] == 0

    def test_chunk_none_split_identical(self, comm):
        off, lshape, sl = comm.chunk((4, 5), None)
        assert off == 0 and lshape == (4, 5)
        assert sl == (slice(0, 4), slice(0, 5))

    def test_chunk_split1(self, comm):
        n = comm.size + 1
        off, lshape, sl = comm.chunk((3, n), 1, 0)
        assert lshape == (3, comm.chunk_size(n))
        assert sl[0] == slice(0, 3)

    def test_lshape_map_sums_to_global(self, comm):
        n = 5 * comm.size + 3
        m = comm.lshape_map((n, 4), 0)
        assert m.shape == (comm.size, 2)
        assert m[:, 0].sum() == n
        assert (m[:, 1] == 4).all()

    def test_lshape_map_replicated(self, comm):
        m = comm.lshape_map((6, 2), None)
        assert (m == np.array([6, 2])).all()

    def test_counts_displs_contract(self, comm):
        for n in (1, comm.size, comm.size + 1, 4 * comm.size + 3):
            counts, displs = comm.counts_displs(n)
            assert len(counts) == len(displs) == comm.size
            assert sum(counts) == n
            assert displs[0] == 0
            for r in range(1, comm.size):
                assert displs[r] == displs[r - 1] + counts[r - 1]

    def test_counts_displs_matches_chunk(self, comm):
        n = 2 * comm.size + 1
        counts, displs = comm.counts_displs(n)
        for r in range(comm.size):
            off, lshape, _ = comm.chunk((n,), 0, r)
            assert counts[r] == lshape[0]
            assert displs[r] == off


class TestShardingFactories:
    def test_spec_none(self, comm):
        assert comm.spec(None, 2) == PartitionSpec()

    def test_spec_places_axis(self, comm):
        s = comm.spec(1, 3)
        assert s == PartitionSpec(None, comm.axis_name, None)

    def test_sharding_is_named(self, comm):
        sh = comm.sharding(0, 2)
        assert isinstance(sh, NamedSharding)
        assert sh.spec == PartitionSpec(comm.axis_name, None)

    def test_replicated(self, comm):
        sh = comm.replicated()
        assert sh.spec == PartitionSpec()

    def test_sharding_lays_out_shards(self, comm):
        x = jnp.arange(4 * comm.size, dtype=jnp.float32)
        xs = jax.device_put(x, comm.sharding(0, 1))
        shapes = {s.data.shape for s in xs.addressable_shards}
        assert shapes == {(4,)}


class TestCollectives:
    """Every explicit collective wrapper, driven inside a real shard_map
    kernel (the reference unit-tests each MPI wrapper directly,
    test_communication.py:1-2467)."""

    def _run(self, comm, kernel, x, ndim=1):
        spec = comm.spec(0, ndim)
        return jax.shard_map(
            kernel, mesh=comm.mesh, in_specs=spec, out_specs=spec
        )(x)

    def test_psum(self, comm):
        x = jnp.ones((comm.size, 2), dtype=jnp.float32)
        out = self._run(comm, lambda v: comm.psum(v), x, ndim=2)
        np.testing.assert_allclose(np.asarray(out), comm.size)

    def test_pmax_pmin(self, comm):
        x = jnp.arange(comm.size, dtype=jnp.float32).reshape(comm.size, 1)
        mx = self._run(comm, lambda v: comm.pmax(v), x, ndim=2)
        mn = self._run(comm, lambda v: comm.pmin(v), x, ndim=2)
        np.testing.assert_allclose(np.asarray(mx), comm.size - 1)
        np.testing.assert_allclose(np.asarray(mn), 0)

    def test_axis_index(self, comm):
        x = jnp.zeros((comm.size, 1), dtype=jnp.int32)
        out = self._run(
            comm, lambda v: v + comm.axis_index().astype(jnp.int32), x, ndim=2
        )
        np.testing.assert_array_equal(np.asarray(out)[:, 0], np.arange(comm.size))

    def test_all_gather_tiled(self, comm):
        p = comm.size
        x = jnp.arange(p, dtype=jnp.float32)

        def kernel(v):  # each shard holds 1 element; gather -> p elements
            g = comm.all_gather(v)
            return g[: v.shape[0]] * 0 + jnp.sum(g, keepdims=True)

        out = self._run(comm, kernel, x)
        np.testing.assert_allclose(np.asarray(out), p * (p - 1) / 2)

    def test_ppermute_shift(self, comm):
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        x = jnp.arange(p, dtype=jnp.float32)
        perm = [(i, (i + 1) % p) for i in range(p)]
        out = self._run(comm, lambda v: comm.ppermute(v, perm), x)
        np.testing.assert_array_equal(np.asarray(out), np.roll(np.arange(p), 1))

    def test_ring_permute_matches_roll(self, comm):
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        x = jnp.arange(p, dtype=jnp.float32)
        for shift in (1, 2):
            out = self._run(comm, lambda v: comm.ring_permute(v, shift), x)
            np.testing.assert_array_equal(
                np.asarray(out), np.roll(np.arange(p), shift)
            )

    def test_ring_permute_full_cycle_identity(self, comm):
        p = comm.size
        x = jnp.arange(p, dtype=jnp.float32)

        def kernel(v):
            for _ in range(p):
                v = comm.ring_permute(v, 1)
            return v

        out = self._run(comm, kernel, x)
        np.testing.assert_array_equal(np.asarray(out), np.arange(p))

    def test_all_to_all_roundtrip_identity(self, comm):
        p = comm.size
        x = jnp.arange(p * p * 2, dtype=jnp.float32).reshape(p, 2 * p)

        def kernel(v):  # v: (1, 2p) — reshard cols then invert
            t = comm.all_to_all(v, split_axis=1, concat_axis=0)
            return comm.all_to_all(t, split_axis=0, concat_axis=1)

        spec = comm.spec(0, 2)
        out = jax.shard_map(
            kernel, mesh=comm.mesh, in_specs=spec, out_specs=spec
        )(x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def test_all_to_all_redistributes_across_shards(self, comm):
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        # every shard must end up holding one piece from every peer
        x = jnp.repeat(jnp.arange(p, dtype=jnp.float32)[:, None], p, axis=1)

        def kernel(v):  # v: (1, p), constant row = own index
            return comm.all_to_all(v, split_axis=1, concat_axis=0)

        spec = comm.spec(0, 2)
        out = jax.shard_map(
            kernel, mesh=comm.mesh, in_specs=spec, out_specs=spec
        )(x)
        for s in out.addressable_shards:
            got = sorted(np.asarray(s.data).ravel().tolist())
            assert got == list(range(p)), got


class TestHaloExchange:
    def test_halo_matches_neighbor_rows(self, comm):
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        n = 2 * p
        x = ht.arange(n, dtype=ht.float32, split=0)
        withh = x.array_with_halos(1)
        # per-shard: [prev_last, own..., next_first]; global buffer length n+2p...
        # check shard 1's first element == shard 0's last element
        shards = sorted(
            withh.addressable_shards, key=lambda s: s.index[0].start or 0
        )
        s0 = np.asarray(shards[0].data)
        s1 = np.asarray(shards[1].data)
        assert s1[0] == s0[-2]  # prev neighbor's last own row
        assert s0[-1] == s1[1]  # next neighbor's first own row

    def test_halo_zero_at_edges(self, comm):
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        x = ht.arange(2 * p, dtype=ht.float32, split=0) + 1.0
        withh = x.array_with_halos(1)
        shards = sorted(
            withh.addressable_shards, key=lambda s: s.index[0].start or 0
        )
        assert np.asarray(shards[0].data)[0] == 0.0  # no left neighbor
        assert np.asarray(shards[-1].data)[-1] == 0.0  # no right neighbor

    def test_halo_requires_positive_size(self, comm):
        x = ht.arange(2 * comm.size, dtype=ht.float32, split=0)
        if comm.size > 1:
            with pytest.raises(ValueError, match="positive"):
                x.array_with_halos(0)

    def test_halo_replicated_passthrough(self, comm):
        x = ht.arange(6, dtype=ht.float32, split=None)
        out = x.array_with_halos(1)
        np.testing.assert_array_equal(np.asarray(out), np.arange(6))


class TestRegistry:
    def test_get_comm_singleton(self):
        assert get_comm() is get_comm()

    def test_sanitize_comm_none(self):
        assert sanitize_comm(None) is get_comm()

    def test_sanitize_comm_passthrough(self, comm):
        assert sanitize_comm(comm) is comm

    def test_sanitize_comm_rejects(self):
        with pytest.raises(TypeError):
            sanitize_comm(42)

    def test_use_comm_rejects(self):
        with pytest.raises(TypeError):
            use_comm("not a comm")

    def test_use_comm_roundtrip(self, comm):
        use_comm(comm)
        assert get_comm() is comm

    def test_repr(self, comm):
        r = repr(comm)
        assert "MeshCommunication" in r and str(comm.size) in r

    def test_eq_hash(self, comm):
        other = MeshCommunication(devices=comm.devices, axis=comm.axis_name)
        assert other == comm
        assert hash(other) == hash(comm)

    def test_is_distributed_single_controller(self, comm):
        assert MeshCommunication.is_distributed() is False
